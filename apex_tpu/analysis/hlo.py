"""Structural parsing of lowered StableHLO text.

The lint rules (``apex_tpu/analysis/rules.py``) need a handful of facts
about a ``jax.jit(...).lower(...)`` artifact that the ad-hoc test greps
(``"callback" not in lowered.as_text()``) approximated badly: WHICH
custom-call targets appear (a comment or a backend_config hex string
containing the substring must not count), which element types any
tensor in the module uses, and the entry computation's argument/result
attributes (``tf.aliasing_output`` donation marks, ``mhlo.sharding``
annotations, ``mhlo.num_partitions``). Everything here is plain-text
parsing — no XLA compile, no device — so a lint stays trace-only.

The parsers are deliberately line-oriented: ``lowered.as_text()`` prints
one op per line, and the few multi-line constructs (the entry signature,
dense constant payloads) are handled explicitly. Unknown constructs
degrade to "not matched", never to an exception — a lint pass must not
crash on an HLO shape it has never seen.
"""

import re

# element-type byte widths for tensor<...> size accounting; anything
# unknown falls back to 4 so a size threshold still has a defined value
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8E4M3FN": 1, "f8E5M2": 1,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1,
    "complex<f32>": 8, "complex<f64>": 16,
}

_TENSOR_RE = re.compile(r"tensor<([^<>]*(?:<[^<>]*>)?[^<>]*)>")
_CUSTOM_CALL_RE = re.compile(r"stablehlo\.custom_call\s+@([\w.$\-]+)")
_NUM_PARTITIONS_RE = re.compile(r"mhlo\.num_partitions\s*=\s*(\d+)")
_SHARDING_ATTR_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_ALIAS_ATTR_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")


def parse_tensor_type(spec):
    """``'8x128xf32'`` -> ``(shape_tuple, dtype_str, nbytes)``.

    Dynamic or otherwise unparseable dimensions yield shape ``None``
    (size unknown -> nbytes 0, so thresholds never fire spuriously).
    """
    parts = spec.strip().split("x")
    dtype = parts[-1]
    dims = parts[:-1]
    shape = []
    for d in dims:
        if not d.isdigit():
            return None, dtype, 0
        shape.append(int(d))
    n = 1
    for d in shape:
        n *= d
    return tuple(shape), dtype, n * _DTYPE_BYTES.get(dtype, 4)


def custom_call_targets(text):
    """``{target_name: count}`` over every ``stablehlo.custom_call``
    in the module — the precise replacement for the substring grep."""
    out = {}
    for m in _CUSTOM_CALL_RE.finditer(text):
        out[m.group(1)] = out.get(m.group(1), 0) + 1
    return out


def num_partitions(text):
    """The module's ``mhlo.num_partitions`` (1 when unannotated)."""
    m = _NUM_PARTITIONS_RE.search(text)
    return int(m.group(1)) if m else 1


def find_dtype_lines(text, dtype):
    """``[(lineno, stripped_line)]`` for lines containing a tensor of
    ``dtype`` — used to name the offending op for the no-f64 rule. The
    match is against parsed tensor types, not the raw substring, so
    ``f64`` inside a constant payload or a name never counts."""
    hits = []
    for i, line in enumerate(text.splitlines(), 1):
        if dtype not in line:
            continue
        for m in _TENSOR_RE.finditer(line):
            if parse_tensor_type(m.group(1))[1] == dtype:
                hits.append((i, line.strip()))
                break
    return hits


def _split_top_level(s, sep=","):
    """Split ``s`` on ``sep`` at bracket depth 0 (handles the nested
    ``tensor<...>`` / ``{...}`` attribute groups in a signature)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "<{([":
            depth += 1
        elif ch in ">})]":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def _balanced_span(text, start):
    """Return the index just past the ``(``...``)`` group opening at
    ``text[start]`` (which must be '(')."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def entry_signature(text):
    """Parse the ``@main`` entry function signature.

    Returns ``{"args": [...], "results": [...]}`` where each entry is
    ``{"type": raw tensor spec or None, "shape", "dtype", "nbytes",
    "sharding": mhlo.sharding or None, "aliased_output": int or None}``
    (results carry no ``aliased_output``). An unparseable signature
    yields empty lists — rules treat that as "no evidence".
    """
    empty = {"args": [], "results": []}
    m = re.search(r"func\.func\s+(?:public\s+)?@main\s*\(", text)
    if not m:
        return empty
    args_open = m.end() - 1
    args_close = _balanced_span(text, args_open)
    args_raw = text[args_open + 1:args_close - 1]
    rest = text[args_close:]
    results_raw = ""
    arrow = re.match(r"\s*->\s*", rest)
    if arrow:
        after = rest[arrow.end():]
        if after.startswith("("):
            results_raw = after[1:_balanced_span(after, 0) - 1]
        else:
            # single un-parenthesized result: up to the opening brace
            results_raw = after.split("{", 1)[0]
            # ... unless the result carries an attribute dict; the
            # parenthesized form is what jax emits, so keep this simple
    sig = {"args": [], "results": []}
    for section, raw in (("args", args_raw), ("results", results_raw)):
        for item in _split_top_level(raw):
            tm = _TENSOR_RE.search(item)
            if tm is None:
                entry = {"type": None, "shape": None, "dtype": None,
                         "nbytes": 0, "sharding": None,
                         "aliased_output": None}
            else:
                shape, dtype, nbytes = parse_tensor_type(tm.group(1))
                sm = _SHARDING_ATTR_RE.search(item)
                am = _ALIAS_ATTR_RE.search(item)
                entry = {"type": tm.group(1), "shape": shape,
                         "dtype": dtype, "nbytes": nbytes,
                         "sharding": sm.group(1) if sm else None,
                         "aliased_output":
                             int(am.group(1)) if am else None}
            sig[section].append(entry)
    return sig


_SHARDING_OP_RE = re.compile(
    r"(%[\w#.]+)\s*=\s*stablehlo\.custom_call\s+@Sharding\((%[\w#.]+)\)")


def sharding_custom_calls(text):
    """``[(lineno, sharding_str, tensor_spec)]`` for every
    ``custom_call @Sharding`` op that is a genuine sharding constraint
    on an intermediate (``with_sharding_constraint`` / committed
    ``device_put`` inside the program).

    ``shard_map`` lowers its input/output marshaling to ``@Sharding``
    ops immediately feeding ``@SPMDFullToShardShape`` (or consuming
    ``@SPMDShardToFullShape``) — those encode the BOUNDARY layout the
    caller asked for (replicated params across a dp mesh is the DDP
    contract, not a blowup), so they are excluded here."""
    lines = text.splitlines()
    # vars produced by shard->full marshaling, and vars consumed by
    # full->shard marshaling: @Sharding ops touching either are
    # shard_map plumbing, not constraints
    shard_to_full_outs = set()
    full_to_shard_ins = set()
    for line in lines:
        if "@SPMDShardToFullShape" in line:
            m = re.match(r"\s*(%[\w#.]+)\s*=", line)
            if m:
                shard_to_full_outs.add(m.group(1))
        if "@SPMDFullToShardShape" in line:
            for var in re.findall(r"@SPMDFullToShardShape\(([^)]*)\)",
                                  line):
                full_to_shard_ins.update(
                    v.strip() for v in var.split(","))
    out = []
    for i, line in enumerate(lines, 1):
        if "custom_call @Sharding" not in line:
            continue
        om = _SHARDING_OP_RE.search(line)
        if om is not None:
            result_var, operand_var = om.group(1), om.group(2)
            if result_var in full_to_shard_ins \
                    or operand_var in shard_to_full_outs:
                continue  # shard_map boundary marshaling
        sm = _SHARDING_ATTR_RE.search(line)
        # the RESULT type is the last tensor<> on the line
        tensors = _TENSOR_RE.findall(line)
        if sm and tensors:
            out.append((i, sm.group(1), tensors[-1]))
    return out


_ARG_SHARDING_RE = re.compile(
    r"(%arg\d+):\s*tensor<[^>]*>\s*\{[^}]*mhlo\.sharding\s*=\s*"
    r'"([^"]*)"')


def arg_shardings(text):
    """``[(lineno, arg_name, sharding_str)]`` for every entry-function
    argument carrying an ``mhlo.sharding`` annotation — the sharded
    roots the collective dataflow analysis walks from (the entry
    signature spans multiple lines on wide programs, so this scans
    every line rather than reparsing the balanced signature)."""
    out = []
    for i, line in enumerate(text.splitlines(), 1):
        if "mhlo.sharding" not in line:
            continue
        for m in _ARG_SHARDING_RE.finditer(line):
            out.append((i, m.group(1), m.group(2)))
    return out


_INTERLEAVE_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|reduce_scatter|all_gather|all_to_all)\b")
_INTERLEAVE_COMPUTE_RE = re.compile(
    r"stablehlo\.(dot_general|dot|convolution)\b")


def collective_compute_interleaving(text):
    """Module-order interleaving of collectives and heavy compute.

    StableHLO text preserves emission (trace) order, so an overlapped
    step — which launches each bucket's collective before tracing the
    earlier segments' backward — shows dot/convolution ops AFTER its
    first collective, while a sync-after-backward step's collectives
    form one trailing block. Returns ``{"collectives", "compute_ops",
    "compute_after_first_collective", "collectives_before_last_compute",
    "interleaved"}``; ``interleaved`` is True iff at least one
    collective precedes at least one compute op AND vice versa. A
    pre-scheduling heuristic (the scheduler may still reorder), used by
    the overlap tests/bench next to the ``overlap-serialization``
    dependence rule — order suggests, dependence proves."""
    coll, comp = [], []
    for i, line in enumerate(text.splitlines()):
        if _INTERLEAVE_COLLECTIVE_RE.search(line):
            coll.append(i)
        if _INTERLEAVE_COMPUTE_RE.search(line):
            comp.append(i)
    after = sum(1 for c in comp if coll and c > coll[0])
    before_last = sum(1 for c in coll if comp and c < comp[-1])
    return {
        "collectives": len(coll),
        "compute_ops": len(comp),
        "compute_after_first_collective": after,
        "collectives_before_last_compute": before_last,
        "interleaved": bool(after and before_last),
    }


def large_constant_bytes(text, min_bytes):
    """``[(lineno, nbytes, tensor_spec)]`` for ``stablehlo.constant``
    ops whose tensor type meets ``min_bytes`` — the text-level fallback
    for the trace-constant rule when no jaxpr is available. Splat
    constants (``dense<0.0e+00>``) are skipped: XLA materializes those
    lazily, they cost compile-time nothing."""
    out = []
    for i, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s.startswith(("%cst", "%c")) or "stablehlo.constant" not in s:
            continue
        m = re.search(r'dense<"', s)
        if m is None:
            continue  # splat or small inline literal
        tensors = _TENSOR_RE.findall(s)
        if not tensors:
            continue
        _, _, nbytes = parse_tensor_type(tensors[-1])
        if nbytes >= min_bytes:
            out.append((i, nbytes, tensors[-1]))
    return out
