"""Whole-program collective dataflow analysis over lowered HLO.

PR 9's rules look at one op or one argument at a time. The SPMD
questions that actually bite on a 2-D mesh are *relational*: which
collectives does the program execute, over which partitions of the
device set, moving how many wire bytes, and do those match what the
source jaxpr authored? GSPMD inserts resharding collective-permutes /
all-to-alls silently, replica groups can diverge into shapes no single
SPMD schedule can execute, and the compressed-collective byte win only
exists if the compiler actually emitted the quantized payload we think
it did.

This module parses every collective op out of HLO text into a
:class:`CollectiveGraph` — nodes carry ``replica_groups`` / channel
ids / operand shapes+dtypes, edges are def-use reachability between
collectives — and computes static per-op and per-program wire bytes
with the same ring model as
:func:`apex_tpu.parallel.compression.estimate_allreduce_bytes` and
:func:`apex_tpu.telemetry.comm.wire_bytes`. Two HLO dialects are
understood:

- **lowered StableHLO** (``jitted.lower(...).as_text()``) — the
  trace-only artifact every lint entrypoint already has. shard_map
  programs carry their collectives explicitly here.
- **post-optimization HLO** (``lowered.compile().as_text()``) — the
  only artifact where GSPMD's *inserted* collectives are visible.
  :func:`audit_spmd` is the explicitly-compiling entrypoint for that
  comparison; everything else in ``apex_tpu.analysis`` stays
  trace-only.

The int8 psum emulation (``parallel/compression.py``) ships int32
partials through XLA today; the *semantic* wire format is int8 +
scales. A reduction collective whose operand is a
``convert(i8 -> i32)`` is therefore counted at 1 byte/element and
tagged ``emulated`` — the same convention ``record_collective`` uses,
so the static total is directly comparable to the bench's
``measured_comm_bytes_per_step`` (the 25% consistency gate in
``bench.py`` depends on the two models staying aligned).
"""

import dataclasses
import re
from typing import Optional

from apex_tpu.analysis import hlo

COLLECTIVE_KINDS = ("all_reduce", "reduce_scatter", "all_gather",
                    "all_to_all", "collective_permute")

# jaxpr collective primitive -> the HLO op kind it lowers to
JAXPR_TO_HLO_KIND = {
    "psum": "all_reduce", "pmax": "all_reduce", "pmin": "all_reduce",
    "reduce_precision_psum": "all_reduce",
    "psum_scatter": "reduce_scatter", "reduce_scatter": "reduce_scatter",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "collective_permute", "pbroadcast": "collective_permute",
}


@dataclasses.dataclass
class CollectiveOp:
    """One collective parsed out of HLO text."""

    kind: str                 # one of COLLECTIVE_KINDS
    func: str                 # enclosing function name
    lineno: int               # 1-based module line
    result: str               # result var (base name)
    operands: tuple           # operand var base names
    operand_specs: tuple      # (shape, dtype, nbytes) per operand
    replica_groups: Optional[tuple] = None   # tuple of device tuples
    source_target_pairs: Optional[tuple] = None
    channel_id: Optional[int] = None
    group_size: int = 1
    payload_bytes: int = 0    # semantic payload (emulation-aware)
    wire_bytes: int = 0       # ring-model bytes each device transmits
    emulated: bool = False    # int8-emulation payload detected
    wire_dtype: str = ""      # semantic wire dtype
    axis_names: Optional[tuple] = None  # best-effort, from the jaxpr
    line: str = ""
    # fused computation-collective custom_call provenance
    # (kernels/fused_cc.py): the target name, and the payload/group
    # the op's frontend attributes declare (0 = not declared)
    custom_target: Optional[str] = None
    attr_payload_bytes: int = 0
    attr_group_size: int = 0

    def to_row(self):
        groups = None
        if self.replica_groups is not None:
            groups = [list(g) for g in self.replica_groups]
        elif self.source_target_pairs is not None:
            groups = [list(p) for p in self.source_target_pairs]
        shape, dtype, _ = (self.operand_specs[0] if self.operand_specs
                           else (None, None, 0))
        row = {
            "op": self.kind, "line": self.lineno,
            "dtype": self.wire_dtype or dtype,
            "shape": list(shape) if shape else None,
            "replica_groups": groups,
            "group_size": self.group_size,
            "channel_id": self.channel_id,
            "payload_bytes": self.payload_bytes,
            "wire_bytes": self.wire_bytes,
            "emulated": self.emulated,
            "axes": list(self.axis_names) if self.axis_names else None,
        }
        if self.custom_target:
            row["custom_target"] = self.custom_target
        return row


# ---------------------------------------------------------------------------
# text-level def-use graph (per function — var names reset per func)
# ---------------------------------------------------------------------------

_FUNC_RE = re.compile(r"func\.func\s+(?:public\s+|private\s+)?@([\w$.\-]+)")
_DEF_RE = re.compile(r"^\s*(%[\w.\-]+)(?::\d+)?\s*=\s*(.*)$")
_VAR_RE = re.compile(r"%[\w.\-]+")


def _qual(func, var):
    return f"{func}:{var}"


class ValueGraph:
    """Def-use over HLO text: qualified var -> (op line text, lineno,
    operand vars); plus consumers for forward walks. Cross-function
    ``call`` edges are not followed — the analyses below only need
    intra-function reachability (collectives and their feeds live in
    one function in every lowering jax produces)."""

    def __init__(self):
        self.defs = {}        # qvar -> (lineno, op_text, operand qvars)
        self.consumers = {}   # qvar -> [result qvar, ...]

    def add(self, func, lineno, result, op_text, operands):
        q = _qual(func, result)
        qops = tuple(_qual(func, o) for o in operands)
        self.defs[q] = (lineno, op_text, qops)
        for o in qops:
            self.consumers.setdefault(o, []).append(q)

    def ancestors(self, qvar):
        seen, stack = set(), [qvar]
        while stack:
            v = stack.pop()
            for o in self.defs.get(v, (0, "", ()))[2]:
                if o not in seen:
                    seen.add(o)
                    stack.append(o)
        return seen

    def descendants(self, qvar):
        seen, stack = set(), [qvar]
        while stack:
            v = stack.pop()
            for c in self.consumers.get(v, ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return seen


def _base_var(tok):
    return tok.split("#", 1)[0]


def build_value_graph(text):
    graph = ValueGraph()
    func = ""
    for i, line in enumerate(text.splitlines(), 1):
        fm = _FUNC_RE.search(line)
        if fm:
            func = fm.group(1)
            continue
        dm = _DEF_RE.match(line)
        if dm is None:
            continue
        result, rest = dm.group(1), dm.group(2)
        operands = tuple({_base_var(v) for v in _VAR_RE.findall(rest)}
                        - {result})
        graph.add(func, i, result, rest, operands)
    return graph


# ---------------------------------------------------------------------------
# replica-group parsing (both dialects)
# ---------------------------------------------------------------------------

_DENSE_GROUPS_RE = re.compile(
    r"replica_groups\s*=\s*dense<([^>]*)>\s*:\s*tensor<([\dx]+)xi64>")
_DENSE_PAIRS_RE = re.compile(
    r"source_target_pairs\s*=\s*dense<([^>]*)>\s*:\s*tensor<([\dx]+)xi64>")
_CHANNEL_STABLE_RE = re.compile(r"channel_handle\s*=\s*#stablehlo\."
                                r"channel_handle<handle\s*=\s*(\d+)")
_CHANNEL_HLO_RE = re.compile(r"channel_id=(\d+)")
_HLO_GROUPS_BRACE_RE = re.compile(r"replica_groups=\{(\{[^}]*\}"
                                  r"(?:,\s*\{[^}]*\})*)\}")
_HLO_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_HLO_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^}]*\}"
                           r"(?:,\s*\{[^}]*\})*)\}")


def _parse_dense_matrix(payload, shape_spec):
    """``dense<[[0, 1], [2, 3]]>`` (or a splat) with its declared
    ``GxSxi64`` shape -> tuple of row tuples, or None if unparseable."""
    dims = [int(d) for d in shape_spec.split("x") if d]
    nums = [int(n) for n in re.findall(r"-?\d+", payload)]
    total = 1
    for d in dims:
        total *= d
    if len(nums) != total or len(dims) != 2:
        return None  # splat over >1 element carries no partition info
    rows, cols = dims
    return tuple(tuple(nums[r * cols:(r + 1) * cols])
                 for r in range(rows))


def _parse_iota_groups(g, s, dims_s, perm_s):
    """Post-opt HLO iota form ``[G,S]<=[d0,d1]T(p0,p1)``: iota over the
    dims, transposed by the perm, reshaped to G groups of S."""
    try:
        import numpy as np

        dims = [int(d) for d in dims_s.split(",") if d]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if perm_s:
            arr = arr.transpose([int(p) for p in perm_s.split(",")])
        flat = arr.reshape(-1)
        g, s = int(g), int(s)
        if g * s != flat.size:
            return None
        return tuple(tuple(int(x) for x in flat[r * s:(r + 1) * s])
                     for r in range(g))
    except Exception:
        return None


def _parse_brace_groups(payload):
    return tuple(tuple(int(n) for n in re.findall(r"-?\d+", grp))
                 for grp in re.findall(r"\{([^}]*)\}", payload))


# ---------------------------------------------------------------------------
# collective parsing
# ---------------------------------------------------------------------------

_STABLE_OP_RE = re.compile(
    r"(%[\w.\-]+)(?::\d+)?\s*=\s*\"?stablehlo\.(" +
    "|".join(COLLECTIVE_KINDS) + r")\"?\s*\(([^)]*)\)")
_SIG_RE = re.compile(r":\s*\(([^)]*)\)\s*->\s*(.+?)\s*$")
_HLO_OP_RE = re.compile(
    r"(%[\w.\-]+)\s*=\s*\(?\s*((?:[a-z0-9]+\[[^\]]*\][^)]*?|\s|,)*?)\)?\s*"
    r"(all-reduce|reduce-scatter|all-gather|all-to-all|collective-permute)"
    r"(?:-start)?\(")
_HLO_TYPE_RE = re.compile(r"([a-z]+\d*(?:e\d+m\d+\w*)?)\[([\d,]*)\]")

# --- fused computation-collective custom_calls (kernels/fused_cc) ---
# A TPU-lowered fused op subsumes its collective into one custom_call;
# the target name says WHICH collective, and the op's frontend
# attributes (``apex_payload_bytes`` / ``apex_group_size``) declare
# the wire payload and ring size the fused kernel moves.  The auditor
# prices these exactly like the named collective — a fused program's
# static_comm_bytes equals its unfused equivalent's, never 0.
# Mirror of kernels/fused_cc.FUSED_CC_CUSTOM_CALL_TARGETS (kept
# textual here: the analysis layer parses HLO, it does not import the
# kernel layer).
FUSED_CC_TARGETS = {
    "apex_fused_cc_matmul_all_reduce": "all_reduce",
    "apex_fused_cc_matmul_reduce_scatter": "reduce_scatter",
    "apex_fused_cc_all_gather_matmul": "all_gather",
    "apex_fused_cc_quant4_all_gather": "all_gather",
}
_STABLE_CUSTOM_RE = re.compile(
    r"(%[\w.\-]+)(?::\d+)?\s*=\s*\"?stablehlo\.custom_call\"?\s*"
    r"(?:@([\w$.\-]+))?\s*\(([^)]*)\)")
_CUSTOM_TARGET_ATTR_RE = re.compile(
    r"call_target_name\s*=\s*\"([^\"]+)\"")
_HLO_CUSTOM_RE = re.compile(
    r"(%[\w.\-]+)\s*=\s*((?:[a-z0-9]+\[[^\]]*\][^(]*?|\s|,)*?)"
    r"custom-call\(")
_HLO_CUSTOM_TARGET_RE = re.compile(
    r"custom_call_target=\"([^\"]+)\"")
_ATTR_PAYLOAD_RE = re.compile(r"apex_payload_bytes\s*=\s*\"?(\d+)\"?")
_ATTR_GROUP_RE = re.compile(r"apex_group_size\s*=\s*\"?(\d+)\"?")


def _spec_from_tensor(spec):
    shape, dtype, nbytes = hlo.parse_tensor_type(spec)
    return (shape, dtype, nbytes)


def _region_signature(lines, start):
    """The ``}) : (types) -> types`` closing line of a region op whose
    opening line is ``lines[start]``. Returns (operand_specs,
    close_lineno) or (None, start)."""
    depth = lines[start].count("({") - lines[start].count("})")
    i = start
    while depth > 0 and i + 1 < len(lines):
        i += 1
        depth += lines[i].count("({") - lines[i].count("})")
    m = _SIG_RE.search(lines[i])
    if m is None:
        return None, start
    specs = tuple(_spec_from_tensor(t)
                  for t in hlo._TENSOR_RE.findall(m.group(1)))
    return specs, i


def _fused_custom_call_stable(line, idx, func):
    """A stablehlo custom_call whose target is a fused
    computation-collective kernel, as a priceable CollectiveOp; None
    for every other line (unknown custom_calls stay unpriced)."""
    m = _STABLE_CUSTOM_RE.search(line)
    if m is None:
        return None
    target = m.group(2)
    if target is None:
        tm = _CUSTOM_TARGET_ATTR_RE.search(line)
        target = tm.group(1) if tm else None
    kind = FUSED_CC_TARGETS.get(target or "")
    if kind is None:
        return None
    operands = tuple(_base_var(v) for v in _VAR_RE.findall(m.group(3)))
    sig = _SIG_RE.search(line)
    specs = tuple(_spec_from_tensor(t) for t in
                  hlo._TENSOR_RE.findall(sig.group(1))) if sig else ()
    groups = None
    gm = _DENSE_GROUPS_RE.search(line)
    if gm:
        groups = _parse_dense_matrix(gm.group(1), gm.group(2))
    pb = _ATTR_PAYLOAD_RE.search(line)
    gs = _ATTR_GROUP_RE.search(line)
    return CollectiveOp(
        kind=kind, func=func, lineno=idx + 1, result=m.group(1),
        operands=operands, operand_specs=specs, replica_groups=groups,
        custom_target=target,
        attr_payload_bytes=int(pb.group(1)) if pb else 0,
        attr_group_size=int(gs.group(1)) if gs else 0,
        line=line.strip())


def _fused_custom_call_hlo(s, idx):
    m = _HLO_CUSTOM_RE.search(s)
    if m is None:
        return None
    tm = _HLO_CUSTOM_TARGET_RE.search(s)
    kind = FUSED_CC_TARGETS.get(tm.group(1)) if tm else None
    if kind is None:
        return None
    result = m.group(1)
    paren = s[m.end() - 1:]
    inner = paren[1:hlo._balanced_span(paren, 0) - 1]
    operands = tuple(_base_var(v) for v in _VAR_RE.findall(inner)
                     if _base_var(v) != result)
    specs = tuple((tuple(int(d) for d in dims.split(",") if d), dt,
                   _nbytes_hlo(dims, dt))
                  for dt, dims in _HLO_TYPE_RE.findall(inner))
    groups = None
    gb = _HLO_GROUPS_BRACE_RE.search(s)
    if gb:
        groups = _parse_brace_groups(gb.group(1))
    pb = _ATTR_PAYLOAD_RE.search(s)
    gs = _ATTR_GROUP_RE.search(s)
    return CollectiveOp(
        kind=kind, func="", lineno=idx + 1, result=result,
        operands=operands, operand_specs=specs, replica_groups=groups,
        custom_target=tm.group(1),
        attr_payload_bytes=int(pb.group(1)) if pb else 0,
        attr_group_size=int(gs.group(1)) if gs else 0,
        line=s)


def _stablehlo_collectives(text, graph):
    lines = text.splitlines()
    func = ""
    ops = []
    for idx, line in enumerate(lines):
        fm = _FUNC_RE.search(line)
        if fm:
            func = fm.group(1)
        m = _STABLE_OP_RE.search(line)
        if m is None:
            fused = _fused_custom_call_stable(line, idx, func)
            if fused is not None:
                ops.append(fused)
            continue
        result, kind, operands_raw = m.group(1), m.group(2), m.group(3)
        operands = tuple(_base_var(v)
                         for v in _VAR_RE.findall(operands_raw))
        sig = _SIG_RE.search(line)
        if sig is not None:
            specs = tuple(_spec_from_tensor(t)
                          for t in hlo._TENSOR_RE.findall(sig.group(1)))
        else:
            specs, _ = _region_signature(lines, idx)
            specs = specs or ()
        groups = None
        gm = _DENSE_GROUPS_RE.search(line)
        if gm:
            groups = _parse_dense_matrix(gm.group(1), gm.group(2))
        pairs = None
        pm = _DENSE_PAIRS_RE.search(line)
        if pm:
            pairs = _parse_dense_matrix(pm.group(1), pm.group(2))
        cm = _CHANNEL_STABLE_RE.search(line)
        ops.append(CollectiveOp(
            kind=kind, func=func, lineno=idx + 1, result=result,
            operands=operands, operand_specs=specs,
            replica_groups=groups, source_target_pairs=pairs,
            channel_id=int(cm.group(1)) if cm else None,
            line=line.strip()))
    return ops


def _hlo_collectives(text, graph):
    """Post-optimization HLO text (``compiled.as_text()``) — the
    dialect where GSPMD's inserted collectives are visible."""
    # post-opt HLO instruction names are unique module-wide, so every
    # var stays qualified under the one "" scope the value graph used
    func = ""
    ops = []
    for idx, line in enumerate(text.splitlines()):
        s = line.strip()
        m = _HLO_OP_RE.search(s)
        if m is None:
            fused = _fused_custom_call_hlo(s, idx)
            if fused is not None:
                ops.append(fused)
            continue
        result, kind = m.group(1), m.group(3).replace("-", "_")
        paren = s[m.end() - 1:]
        inner = paren[1:hlo._balanced_span(paren, 0) - 1]
        operands = tuple(_base_var(v) for v in _VAR_RE.findall(inner)
                         if _base_var(v) != result)
        specs = tuple((tuple(int(d) for d in dims.split(",") if d),
                       dt,
                       _nbytes_hlo(dims, dt))
                      for dt, dims in _HLO_TYPE_RE.findall(inner))
        groups = None
        gb = _HLO_GROUPS_BRACE_RE.search(s)
        if gb:
            groups = _parse_brace_groups(gb.group(1))
        else:
            gi = _HLO_GROUPS_IOTA_RE.search(s)
            if gi:
                groups = _parse_iota_groups(*gi.groups())
        pairs = None
        pp = _HLO_PAIRS_RE.search(s)
        if pp:
            pairs = _parse_brace_groups(pp.group(1))
        cm = _CHANNEL_HLO_RE.search(s)
        ops.append(CollectiveOp(
            kind=kind, func=func, lineno=idx + 1, result=result,
            operands=operands, operand_specs=specs,
            replica_groups=groups, source_target_pairs=pairs,
            channel_id=int(cm.group(1)) if cm else None,
            line=s))
    return ops


def _nbytes_hlo(dims_s, dtype):
    n = 1
    for d in dims_s.split(","):
        if d:
            n *= int(d)
    return n * hlo._DTYPE_BYTES.get(dtype, 4)


# ---------------------------------------------------------------------------
# the ring wire model (shared convention with telemetry.comm)
# ---------------------------------------------------------------------------

_EMU_CONVERT_RE = re.compile(r"stablehlo\.convert\b.*"
                             r"tensor<[\dx]*x?i8>\)?\s*->")


def _semantic_payload(op, graph):
    """(payload_bytes, wire_dtype, emulated): the semantic wire payload
    of a collective. A reduction whose operand comes from a
    ``convert(i8 -> i32)`` is the int8 psum emulation — count it at 1
    byte/element (the wire format a production quantized collective
    ships; same convention as ``record_collective``)."""
    total = 0
    dtype = ""
    emulated = False
    for var, spec in zip(op.operands, op.operand_specs):
        shape, dt, nbytes = spec
        elements = 1
        for d in (shape or ()):
            elements *= d
        if dt in ("i32", "ui32") and op.kind in ("all_reduce",
                                                 "reduce_scatter"):
            src = graph.defs.get(_qual(op.func, var))
            if src is not None and _EMU_CONVERT_RE.search(src[1]):
                nbytes = elements  # 1 byte/elem — the semantic payload
                dt = "i8"
                emulated = True
        total += nbytes
        dtype = dtype or dt
    return total, dtype, emulated


def wire_bytes_for(kind, payload_bytes, group_size, *, n_pairs=0):
    """Ring-model bytes each device transmits — the same per-op
    convention as ``telemetry.comm.wire_bytes`` (all_gather payloads
    are per-shard operands, so the factor is ``g-1`` not
    ``(g-1)/g``)."""
    g = group_size
    if kind == "collective_permute":
        return float(payload_bytes) if n_pairs else 0.0
    if g <= 1:
        return 0.0
    if kind == "all_reduce":
        return 2.0 * (g - 1) / g * payload_bytes
    if kind == "all_gather":
        return float((g - 1) * payload_bytes)
    # reduce_scatter / all_to_all: one ring phase over the full payload
    return (g - 1) / g * payload_bytes


class CollectiveGraph:
    """The program's collectives plus def-use reachability edges
    between them — node ``i`` feeds node ``j`` iff some dataflow path
    connects them without passing through a third collective."""

    def __init__(self, ops, graph, num_partitions=1):
        self.ops = list(ops)
        self.value_graph = graph
        self.num_partitions = num_partitions
        by_result = {_qual(op.func, op.result): i
                     for i, op in enumerate(self.ops)}
        self.edges = []
        for i, op in enumerate(self.ops):
            seen = set()
            stack = [_qual(op.func, op.result)]
            while stack:
                v = stack.pop()
                for c in graph.consumers.get(v, ()):
                    if c in seen:
                        continue
                    seen.add(c)
                    j = by_result.get(c)
                    if j is not None:
                        self.edges.append((i, j))
                    else:
                        stack.append(c)

    @property
    def total_wire_bytes(self):
        return int(round(sum(op.wire_bytes for op in self.ops)))

    def device_set(self):
        devices = set()
        for op in self.ops:
            for grp in op.replica_groups or ():
                devices.update(grp)
            for pair in op.source_target_pairs or ():
                devices.update(pair)
        if self.num_partitions > 1:
            devices.update(range(self.num_partitions))
        return devices

    def to_rows(self):
        return [op.to_row() for op in self.ops]


def collective_graph(text):
    """Parse ``text`` (lowered StableHLO or post-opt HLO) into a
    :class:`CollectiveGraph` with per-op semantic payloads and ring
    wire bytes filled in. Unknown constructs degrade to "not matched"
    — same contract as the rest of the text parsers."""
    graph = build_value_graph(text)
    is_stablehlo = "stablehlo" in text or "func.func" in text
    ops = (_stablehlo_collectives(text, graph) if is_stablehlo
           else _hlo_collectives(text, graph))
    for op in ops:
        if op.replica_groups:
            op.group_size = max((len(g) for g in op.replica_groups),
                                default=1)
        elif op.attr_group_size:
            # fused custom_call: the ring size its frontend attribute
            # declares (no replica_groups on a custom_call)
            op.group_size = op.attr_group_size
        elif op.kind == "collective_permute":
            op.group_size = len({d for p in (op.source_target_pairs
                                             or ()) for d in p}) or 1
        payload, dtype, emulated = _semantic_payload(op, graph)
        if op.attr_payload_bytes:
            # fused custom_call: the declared wire payload wins over
            # the operand bytes (the op's operands include the
            # non-collective GEMM inputs)
            payload = op.attr_payload_bytes
        op.payload_bytes = int(payload)
        op.wire_dtype = dtype
        op.emulated = emulated
        op.wire_bytes = int(round(wire_bytes_for(
            op.kind, payload, op.group_size,
            n_pairs=len([p for p in (op.source_target_pairs or ())
                         if p and p[0] != p[-1]]))))
    return CollectiveGraph(ops, graph,
                           num_partitions=hlo.num_partitions(text))


def static_comm_bytes(text):
    """Static per-program wire bytes (each device transmits) for one
    execution of the lowered program — the number ``bench.py`` stamps
    as ``static_comm_bytes_per_step`` next to the trace-measured
    ``measured_comm_bytes_per_step``."""
    return collective_graph(text).total_wire_bytes


def static_comm_bytes_by_axis(text, closed_jaxpr=None):
    """Static ring-model wire bytes grouped by the mesh axis name(s)
    each collective reduces over (axes attached from the source jaxpr
    via :func:`annotate_axes`; ops the best-effort labeling cannot
    match land under ``"?"``). On a 2-D ``(data, model)`` mesh this is
    the static side of the per-axis comm accounting — compressed DP
    grad bytes vs fp32 TP activation bytes — that ``bench.py``'s
    ``tp_dp`` config cross-validates against the trace-measured
    ``comm/axis/<name>_bytes`` counters."""
    graph = collective_graph(text)
    if closed_jaxpr is not None:
        annotate_axes(graph, closed_jaxpr)
    out = {}
    for op in graph.ops:
        key = ",".join(op.axis_names) if op.axis_names else "?"
        out[key] = out.get(key, 0.0) + op.wire_bytes
    return {k: int(round(v)) for k, v in sorted(out.items())}


# ---------------------------------------------------------------------------
# jaxpr side: what the source program authored
# ---------------------------------------------------------------------------

def jaxpr_collective_counts(jaxpr):
    """``{hlo_kind: count}`` of the collectives the source jaxpr
    authored (recursing into sub-jaxprs) — the baseline the
    implicit-reshard rule compares the HLO text against."""
    from apex_tpu.analysis.rules import _iter_subjaxprs

    counts = {}

    def walk(j):
        for eqn in j.eqns:
            kind = JAXPR_TO_HLO_KIND.get(eqn.primitive.name)
            if kind is not None:
                counts[kind] = counts.get(kind, 0) + 1
            for sub in _iter_subjaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return counts


def jaxpr_collective_axes(jaxpr):
    """Ordered ``[(hlo_kind, axes)]`` for best-effort axis labeling of
    parsed text collectives (matched by order within kind)."""
    from apex_tpu.analysis.rules import _collective_axes, _iter_subjaxprs

    out = []

    def walk(j):
        for eqn in j.eqns:
            kind = JAXPR_TO_HLO_KIND.get(eqn.primitive.name)
            if kind is not None:
                out.append((kind, _collective_axes(eqn)))
            for sub in _iter_subjaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return out


def _jaxpr_collective_operands(jaxpr):
    """Ordered ``[(hlo_kind, axes, raw_operand_nbytes)]`` with one entry
    per *operand* of each collective eqn. A multi-leaf ``lax.psum``
    (e.g. a whole gradient tree in one call) is a single jaxpr eqn but
    jax lowers it to one single-operand ``all_reduce`` per leaf — the
    per-operand expansion is what lines the two sides up."""
    from apex_tpu.analysis.rules import _collective_axes, _iter_subjaxprs

    out = []

    def walk(j):
        for eqn in j.eqns:
            kind = JAXPR_TO_HLO_KIND.get(eqn.primitive.name)
            if kind is not None:
                axes = _collective_axes(eqn)
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    nbytes = 0
                    if aval is not None and hasattr(aval, "shape"):
                        nbytes = 1
                        for d in aval.shape:
                            nbytes *= int(d)
                        nbytes *= int(
                            getattr(aval.dtype, "itemsize", 4))
                    out.append((kind, axes, nbytes))
            for sub in _iter_subjaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return out


def annotate_axes(graph, closed_jaxpr):
    """Attach jaxpr axis names to the graph's ops by a size-aware
    subsequence alignment within each kind: jaxpr eqns expand to one
    entry per operand (a multi-leaf ``psum`` lowers to per-leaf
    ``all_reduce`` ops), and the text ops are matched in order against
    the first entry of equal raw operand bytes — skipping jaxpr
    entries the lowering deduplicated (identical recomputed psums CSE
    away between jaxpr and StableHLO). Falls back to plain in-order
    assignment when sizes never line up."""
    if closed_jaxpr is None:
        return graph
    per_kind = {}
    for kind, axes, nbytes in _jaxpr_collective_operands(
            closed_jaxpr.jaxpr):
        per_kind.setdefault(kind, []).append((axes, nbytes))
    cursor = {k: 0 for k in per_kind}
    for op in graph.ops:
        lst = per_kind.get(op.kind)
        if not lst:
            continue
        i = cursor[op.kind]
        if i >= len(lst):
            continue
        raw = sum(spec[2] for spec in op.operand_specs)
        j = i
        while j < len(lst) and raw and lst[j][1] != raw:
            j += 1
        if j < len(lst) and raw and lst[j][1] == raw:
            op.axis_names = tuple(str(a) for a in lst[j][0])
            cursor[op.kind] = j + 1
        else:
            # sizes never line up from here (reshaped/fused payloads):
            # degrade to the old in-order pairing for this op
            op.axis_names = tuple(str(a) for a in lst[i][0])
            cursor[op.kind] = i + 1
    return graph


# ---------------------------------------------------------------------------
# the explicitly-compiling audit (GSPMD's insertions are only visible
# post-partitioning)
# ---------------------------------------------------------------------------

def audit_spmd(fn, *args, rules=("implicit-reshard",
                                 "replica-group-consistency",
                                 "comm-budget"),
               config=None, name=None, **kwargs):
    """Compile ``fn`` and lint its POST-SPMD-partitioning HLO against
    the source jaxpr: the one place a GSPMD-inserted resharding
    collective-permute / all-to-all is visible as an op. This is the
    deliberate exception to the package's trace-only contract — it
    calls ``.compile()`` (use tiny shapes; the partitioner's insertions
    are shape-independent) — so it lives here behind an explicit name
    rather than inside ``lint_fn``. Returns a
    :class:`~apex_tpu.analysis.lint.LintReport`."""
    import jax

    from apex_tpu.analysis.lint import LintContext, run_rules

    jitted = fn if hasattr(fn, "trace") else jax.jit(fn)
    traced = jitted.trace(*args, **kwargs)
    compiled = traced.lower().compile()
    ctx = LintContext(
        hlo_text=compiled.as_text(),
        name=name or getattr(fn, "__name__", "") or "<fn>",
        closed_jaxpr=traced.jaxpr)
    return run_rules(ctx, rules=list(rules), config=config)


def comm_table(ctx):
    """Per-program collective table rows (dicts) for a prepared
    :class:`~apex_tpu.analysis.lint.LintContext` — what
    ``tools/hlo_lint.py --comm`` renders. Cached on the context so the
    rules and the table share one parse."""
    graph = graph_for_context(ctx)
    annotate_axes(graph, ctx.closed_jaxpr)
    return graph.to_rows()


def graph_for_context(ctx):
    """The context's :class:`CollectiveGraph`, parsed once and cached —
    all four sharding rules and :func:`comm_table` share it."""
    graph = getattr(ctx, "_collective_graph", None)
    if graph is None:
        graph = collective_graph(ctx.hlo_text)
        ctx._collective_graph = graph
    return graph
