"""The lint driver: build a :class:`LintContext` from a jitted function
or an already-lowered artifact, run the rule catalog, return a
structured :class:`LintReport`.

Three entrypoints, in decreasing order of evidence:

- :func:`lint_fn` — trace the function (``jit(fn).trace(*args)``, no
  compile) and lint with the FULL context: StableHLO text, closed
  jaxpr, argument pytree (donation flags + concrete buffers). Every
  rule runs.
- :func:`lint_lowered` — lint an existing ``Lowered`` (the
  CompileWatcher / bench path: the lowering already exists, re-tracing
  would double the cost). Jaxpr-needing rules that can't run are
  reported as *skipped*, and the trace-constant rule falls back to the
  text parser.
- :func:`assert_clean_hlo` — the test/CI primitive next to
  ``assert_no_recompiles``: lint and raise :class:`HloLintError`
  naming every finding (rule, op/argument path, message) when any
  rule fires. ``rules=`` selects a subset, ``waive=`` excludes.

Everything is host-side and trace-only: linting never compiles, never
executes, and never mutates the function under test.
"""

import jax

from apex_tpu.analysis.rules import RULES, Finding, LintConfig  # noqa: F401


class HloLintError(AssertionError):
    """Raised by :func:`assert_clean_hlo` when a rule fires. Subclasses
    AssertionError so pytest reports it as a plain test failure."""


class LintContext:
    """Everything a rule may look at. ``hlo_text`` is always present;
    ``closed_jaxpr`` / ``flat_args_info`` / ``flat_args`` /
    ``out_avals`` are None when the entrypoint couldn't provide them
    (rules needing them are skipped)."""

    def __init__(self, *, hlo_text, name="", closed_jaxpr=None,
                 flat_args_info=None, flat_args=None, out_avals=None):
        self.hlo_text = hlo_text
        self.name = name
        self.closed_jaxpr = closed_jaxpr
        self.flat_args_info = flat_args_info
        self.flat_args = flat_args
        self.out_avals = out_avals


class LintReport:
    """Findings plus which rules ran — a skipped rule is visible, never
    a silent pass."""

    def __init__(self, name, findings, rules_run, rules_skipped):
        self.name = name
        self.findings = list(findings)
        self.rules_run = tuple(rules_run)
        self.rules_skipped = tuple(rules_skipped)

    @property
    def ok(self):
        return not self.findings

    def counts(self):
        """``{rule: finding_count}`` over every rule that ran."""
        out = {r: 0 for r in self.rules_run}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def render(self):
        head = (f"hlo lint[{self.name or '<fn>'}]: "
                f"{len(self.findings)} violation(s), "
                f"{len(self.rules_run)} rule(s) run"
                + (f", skipped: {', '.join(self.rules_skipped)}"
                   if self.rules_skipped else ""))
        return "\n".join([head] + [f"  - {f}" for f in self.findings])

    def to_dict(self):
        return {"name": self.name,
                "violations": len(self.findings),
                "rules_run": list(self.rules_run),
                "rules_skipped": list(self.rules_skipped),
                "findings": [f.to_dict() for f in self.findings]}


def _leaf_path_str(path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _flatten_with_paths(tree, root=""):
    flat = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        p = _leaf_path_str(path)
        flat.append((f"{root}/{p}" if p else root or "arg", leaf))
    return flat


def _select_rules(rules=None, waive=()):
    if rules is None:
        names = list(RULES)
    else:
        names = [rules] if isinstance(rules, str) else list(rules)
        unknown = [n for n in names if n not in RULES]
        if unknown:
            raise ValueError(
                f"unknown lint rule(s) {unknown}; known: {list(RULES)}")
    waive = {waive} if isinstance(waive, str) else set(waive or ())
    return [n for n in names if n not in waive]


def run_rules(ctx, *, rules=None, waive=(), config=None):
    """Run the selected rules over a prepared context."""
    cfg = config or LintConfig()
    findings, ran, skipped = [], [], []
    for name in _select_rules(rules, waive):
        fn, _needs = RULES[name]
        out = fn(ctx, cfg)
        if out is None:  # the rule's required artifact is missing
            skipped.append(name)
            continue
        ran.append(name)
        findings.extend(out[:cfg.max_findings_per_rule])
    return LintReport(ctx.name, findings, ran, skipped)


def _is_staged(fn):
    return hasattr(fn, "trace") and hasattr(fn, "lower")


def _flat_out_info(staged):
    """Flat leaf list of a Traced/Lowered ``out_info`` pytree (a bare
    OutInfo for single-output functions), or None when unavailable."""
    try:
        info = staged.out_info
    except Exception:
        return None
    leaves = jax.tree_util.tree_leaves(
        info, is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype"))
    return [o for o in leaves
            if hasattr(o, "shape") and hasattr(o, "dtype")] or None


def build_context(fn, *args, name=None, **kwargs):
    """Trace ``fn`` (jitted or plain; plain functions are wrapped in
    ``jax.jit``) on ``args``/``kwargs`` and return the full
    :class:`LintContext` — the shared front half of :func:`lint_fn`,
    exposed so a caller that needs both the rule report AND the
    collective table (``tools/hlo_lint.py --comm``) traces once.
    Trace-only — nothing compiles."""
    # a watched function (CompileWatcher) delegates trace/lower to the
    # wrapped pjit, so it counts as staged; only plain callables get a
    # fresh jit wrapper here (never unwrap: jit sets __wrapped__ to the
    # plain function, and unwrapping would drop donate_argnums)
    jitted = fn if _is_staged(fn) else jax.jit(fn)
    traced = jitted.trace(*args, **kwargs)
    lowered = traced.lower()
    args_info, kwargs_info = traced.args_info
    flat_info = (_flatten_with_paths(tuple(args_info), "args")
                 + _flatten_with_paths(dict(kwargs_info), "kwargs"))
    flat_vals = (_flatten_with_paths(tuple(args), "args")
                 + _flatten_with_paths(dict(kwargs), "kwargs"))
    # align values to info by path (donated consts can drop from one
    # side in exotic cases; a mismatch degrades double-donation to a
    # path-keyed subset rather than crashing the lint)
    val_by_path = dict(flat_vals)
    flat_args = [(p, val_by_path.get(p)) for p, _ in flat_info]
    return LintContext(
        hlo_text=lowered.as_text(),
        name=name or getattr(fn, "__name__", "") or "<fn>",
        closed_jaxpr=traced.jaxpr,
        flat_args_info=flat_info,
        flat_args=flat_args,
        out_avals=_flat_out_info(traced),
    )


def lint_fn(fn, *args, rules=None, waive=(), config=None, name=None,
            **kwargs):
    """Trace ``fn`` and lint with full context. Returns a
    :class:`LintReport`. Trace-only — nothing compiles."""
    ctx = build_context(fn, *args, name=name, **kwargs)
    return run_rules(ctx, rules=rules, waive=waive, config=config)


def lint_lowered(lowered, *, rules=None, waive=(), config=None,
                 name=None):
    """Lint an existing ``jax.stages.Lowered``. Rules that need the
    jaxpr or concrete arguments are skipped (visible in the report);
    the trace-constant rule falls back to the HLO-text parser."""
    try:
        args_info, kwargs_info = lowered.args_info
        flat_info = (_flatten_with_paths(tuple(args_info), "args")
                     + _flatten_with_paths(dict(kwargs_info), "kwargs"))
    except Exception:
        flat_info = None
    out_avals = _flat_out_info(lowered)
    ctx = LintContext(
        hlo_text=lowered.as_text(),
        name=name or "<lowered>",
        flat_args_info=flat_info,
        out_avals=out_avals,
    )
    return run_rules(ctx, rules=rules, waive=waive, config=config)


def assert_clean_hlo(fn, *args, rules=None, waive=(), config=None,
                     name=None, **kwargs):
    """Lint ``fn(*args, **kwargs)`` and raise :class:`HloLintError`
    listing every finding when a rule fires; return the (clean)
    :class:`LintReport` otherwise.

    The CI primitive next to ``assert_no_recompiles``: replace

        assert "callback" not in jitted.lower(x).as_text()

    with

        assert_clean_hlo(jitted, x, rules="no-host-callback")

    — the rule matches actual ``custom_call`` targets, so a substring
    in a comment or backend_config can neither pass nor fail it."""
    report = lint_fn(fn, *args, rules=rules, waive=waive, config=config,
                     name=name, **kwargs)
    if not report.ok:
        raise HloLintError(report.render())
    return report
