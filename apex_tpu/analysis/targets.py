"""Canonical lintable hot-path steps.

``tools/hlo_lint.py`` and the tier-1 clean-pass tests need the repo's
REAL hot paths — the DDP fp32 / int8 train steps, the ZeRO optimizer
step, the guarded step, the serving decode step — as lowerable
functions at a size the 1-core CPU host traces in seconds. This module
builds them once, through the same ``DistributedDataParallel`` /
``DistributedFusedAdam`` / ``guarded_update`` / ``ServeEngine``
machinery the benches use (a lint target that re-implements the path
would prove nothing), batch data passed as proper arguments and carry
state donated — the idiom the rules enforce.

Each builder returns ``(fn, args, kwargs)`` ready for
:func:`apex_tpu.analysis.lint_fn` / ``assert_clean_hlo``. ``TARGETS``
maps config name -> builder; everything needs the >= 2-device mesh
(the virtual 8-device CPU platform in tests/the CLI).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def _mesh(axis_name="dp"):
    devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def _mlp_params(hidden=32, depth=2, seed=0):
    rng = np.random.RandomState(seed)
    params = {}
    for i in range(depth):
        params[f"w{i}"] = jnp.asarray(
            rng.randn(hidden, hidden).astype(np.float32)
            / np.sqrt(hidden))
        params[f"b{i}"] = jnp.zeros((hidden,), jnp.float32)
    return params


def _mlp_loss(params, xb, yb, depth=2):
    h = xb
    for i in range(depth):
        h = jnp.tanh(h @ params[f"w{i}"] + params[f"b{i}"])
    return jnp.mean((h - yb) ** 2)


def _batch(mesh, hidden=32, batch=4, seed=1):
    rng = np.random.RandomState(seed)
    n = batch * mesh.devices.size
    x = jnp.asarray(rng.randn(n, hidden).astype(np.float32))
    y = jnp.asarray(rng.randn(n, hidden).astype(np.float32))
    return x, y


def ddp_fp32_step():
    """Plain fp32 DDP train step: shard_map over the dp mesh, exact
    psum gradient sync, params donated, batch passed as arguments."""
    from apex_tpu.parallel import DistributedDataParallel

    mesh = _mesh()
    params = _mlp_params()
    x, y = _batch(mesh)
    ddp = DistributedDataParallel(axis_name="dp")

    def step_fn(p, xb, yb):
        loss, grads = jax.value_and_grad(_mlp_loss)(p, xb, yb)
        grads = ddp.sync(grads)
        p = jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, p, grads)
        return p, loss

    sharded = jax.shard_map(step_fn, mesh=mesh,
                            in_specs=(P(), P("dp"), P("dp")),
                            out_specs=(P(), P()), check_vma=False)
    train_step = jax.jit(sharded, donate_argnums=(0,))
    return train_step, (params, x, y), {}


def ddp_int8_step():
    """Int8 block-quantized DDP train step with error feedback — the
    compressed-collective hot path (params AND the EF residual are
    carry state, both donated)."""
    from apex_tpu.parallel import DistributedDataParallel

    mesh = _mesh()
    params = _mlp_params()
    x, y = _batch(mesh)
    ddp = DistributedDataParallel(axis_name="dp", compress="int8")
    residual = ddp.init_residual(params)

    def step_fn(p, res, xb, yb):
        loss, grads = jax.value_and_grad(_mlp_loss)(p, xb, yb)
        grads, res = ddp.sync(grads, res)
        p = jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, p, grads)
        return p, res, loss

    sharded = jax.shard_map(step_fn, mesh=mesh,
                            in_specs=(P(), P(), P("dp"), P("dp")),
                            out_specs=(P(), P(), P()), check_vma=False)
    train_step = jax.jit(sharded, donate_argnums=(0, 1))
    return train_step, (params, residual, x, y), {}


def ddp_overlapped_step():
    """The overlapped int8 DDP train step (parallel/overlap.py): a
    2-segment MLP, segment-by-segment backward with per-bucket psum
    emission — the step the ``overlap-serialization`` rule exists to
    keep honest (every bucket's collective independent; carry state —
    params, bucket-domain EF residual — donated)."""
    from apex_tpu.parallel import OverlappedDataParallel

    mesh = _mesh()
    depth = 2
    params = _mlp_params(depth=depth)
    x, y = _batch(mesh)
    odp = OverlappedDataParallel(axis_name="dp", compress="int8")
    seg_params = [{f"w{i}": params[f"w{i}"], f"b{i}": params[f"b{i}"]}
                  for i in range(depth)]
    residual = odp.init_residual(seg_params)

    def step_fn(sp, res, xb, yb):
        segs = [lambda pk, h, i=i: jnp.tanh(h @ pk[f"w{i}"]
                                            + pk[f"b{i}"])
                for i in range(depth - 1)]

        def last(pk, h, i=depth - 1):
            h = jnp.tanh(h @ pk[f"w{i}"] + pk[f"b{i}"])
            return jnp.mean((h - yb) ** 2)

        segs.append(last)
        loss, synced, new_res = odp.value_and_sync(segs, sp, xb,
                                                   residual=res)
        sp = [jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, pk, gk)
              for pk, gk in zip(sp, synced)]
        return sp, new_res, loss

    sharded = jax.shard_map(step_fn, mesh=mesh,
                            in_specs=(P(), P(), P("dp"), P("dp")),
                            out_specs=(P(), P(), P()), check_vma=False)
    train_step = jax.jit(sharded, donate_argnums=(0, 1))
    return train_step, (seg_params, residual, x, y), {}


def zero_step():
    """ZeRO optimizer step (DistributedFusedAdam with int8 grad
    reduce-scatter): sharded state carried and donated."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    mesh = _mesh()
    params = _mlp_params()
    x, y = _batch(mesh)
    opt = DistributedFusedAdam(lr=1e-2, compress=True)

    def step_fn(p, state, xb, yb):
        loss, grads = jax.value_and_grad(_mlp_loss)(p, xb, yb)
        p, state = opt.step(grads, state, p)
        return p, state, loss

    sharded = jax.shard_map(step_fn, mesh=mesh,
                            in_specs=(P(), P(), P("dp"), P("dp")),
                            out_specs=(P(), P(), P()), check_vma=False)
    train_step = jax.jit(sharded, donate_argnums=(0, 1))

    with mesh:
        state = jax.jit(
            lambda p: jax.shard_map(
                opt.init, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)(p))(params)
    return train_step, (params, state, x, y), {}


def guarded_step():
    """The resilience hot path: guarded int8 DDP step with the NaN
    injection checkpoint armed (step index traced) — the exact shape
    test_resilience pins callback-free."""
    from apex_tpu import resilience
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.resilience import faults

    mesh = _mesh()
    params = _mlp_params()
    x, y = _batch(mesh)
    ddp = DistributedDataParallel(axis_name="dp", compress="int8")
    residual = ddp.init_residual(params)
    gstate = resilience.init_guard_state()

    def step_fn(p, res, gst, step, xb, yb):
        loss, grads = jax.value_and_grad(_mlp_loss)(p, xb, yb)
        grads = faults.inject_nan(grads, step, nan_step=None)
        flag = resilience.nonfinite_flag(grads)
        synced, new_res = ddp.sync(grads, res)

        def commit(g, st):
            prev_p, _ = st
            new_p = jax.tree_util.tree_map(
                lambda w, gg: w - 0.05 * gg, prev_p, g)
            return (new_p, new_res)

        (p, res), gst = resilience.guarded_update(
            synced, commit, (p, res), gst, axis_name="dp", flag=flag)
        return p, res, gst, loss

    sharded = jax.shard_map(step_fn, mesh=mesh,
                            in_specs=(P(), P(), P(), P(), P("dp"),
                                      P("dp")),
                            out_specs=(P(), P(), P(), P()),
                            check_vma=False)
    train_step = jax.jit(sharded, donate_argnums=(0, 1, 2))
    return train_step, (params, residual, gstate,
                        jnp.zeros((), jnp.int32), x, y), {}


def _tp_dp_pieces(mode):
    """Shared tp_dp target construction: a 2x4 ``(data, model)`` mesh
    running the real GPT-2 column/row-parallel block stack
    (apex_tpu.parallel.mesh2d — the tensor_parallel.mappings region
    ops), int8 DP gradient compression + EF residual scoped to the
    ``data`` axis, carry state donated, batch sharded over ``data``."""
    from apex_tpu.parallel import mesh2d

    devices = jax.devices()
    if len(devices) % 2 != 0:
        raise RuntimeError(
            f"tp_dp target needs an even device count, got "
            f"{len(devices)} (run under the virtual 8-device mesh)")
    mesh = mesh2d.mesh_2d(2)
    hidden, heads, vocab, seq = 32, 4, 64, 8
    seg_params = mesh2d.gpt2_init(hidden=hidden, layers=2, heads=heads,
                                  vocab=vocab, max_seq=seq)
    step, state = mesh2d.build_train_step(
        mesh, seg_params, hidden=hidden, heads=heads, mode=mode)
    tokens, labels = mesh2d.make_batch(mesh, batch_per_replica=2,
                                       seq=seq, vocab=vocab)
    return step, state + (tokens, labels), {}


def tp_dp_overlap_min_bytes():
    """The MEANINGFUL ``overlap-serialization`` threshold for the
    tp_dp targets: the smallest DP bucket's int32-partial payload —
    above the TP activation psum payload (so the inherent
    backward-chain TP psums neither taint nor trip) and exactly at the
    bucket floor (so every DP bucket is checked for serialization)."""
    from apex_tpu.parallel import mesh2d

    seg_params = mesh2d.gpt2_init(hidden=32, layers=2, heads=4,
                                  vocab=64, max_seq=8)
    tp = max(1, len(jax.devices()) // 2)
    min_bucket = 4 * min(
        int(sum(l.size for l in jax.tree_util.tree_leaves(seg)))
        for seg in mesh2d.local_template(seg_params, tp))
    tp_psum = 2 * 8 * 32 * 4  # batch_local x seq x hidden fp32
    if tp_psum >= min_bucket:
        raise RuntimeError(
            f"tp_dp target sizing breaks the separation: TP psum "
            f"{tp_psum} B >= smallest bucket {min_bucket} B")
    return min_bucket


def tp_dp_step():
    """The 2-D mesh baseline (ROADMAP item 4): GPT-2 column/row-parallel
    attention + MLP blocks on a 2x4 ``(data, model)`` mesh — TP psums
    over ``model`` joining row-parallel partials (fp32 activations),
    full backward then the bucketed int8 DP grad sync over ``data``.
    The point of the target: every rule — including the four SPMD
    communication rules — must hold on a mesh where two collective
    families with DIFFERENT replica-group partitions of the same 8
    devices coexist in one program."""
    return _tp_dp_pieces("baseline")


def tp_dp_overlapped_step():
    """The overlapped 2-D step (the tentpole composition): per-layer
    segments whose backward emits each DP bucket's compressed psum
    mid-backward, interleaving with the remaining segments' TP psums —
    the ``overlap-serialization`` rule is the static proof obligation
    that no DP bucket chains behind another large reduction (TP
    activation psums sit below the threshold; see
    docs/parallelism.md "2-D mesh composition")."""
    return _tp_dp_pieces("overlapped")


def pp_tp_dp_step():
    """The 3-D mesh composition (ISSUE 17): stage-partitioned GPT-2 on
    a 2x2x2 ``(data, model, pipe)`` mesh under the host-unrolled 1F1B
    schedule — per-tick ``collective_permute`` stage transfers over
    ``pipe``, TP activation psums over ``model`` inside every stage,
    the tied-edge pipe psum, and the bucketed int8 DP grad sync over
    ``data`` traced into the cooldown tail. THREE collective families
    with three different replica-group partitions of the same 8
    devices coexist in one program; every rule must still hold."""
    from apex_tpu.parallel import mesh2d, pipeline

    devices = jax.devices()
    if len(devices) % 8:
        raise RuntimeError(
            f"pp_tp_dp target needs an 8-divisible device count, got "
            f"{len(devices)} (run under the virtual 8-device mesh)")
    mesh = pipeline.mesh_3d(2, 2, 2)
    hidden, heads, vocab, seq = 32, 4, 64, 8
    seg_params = mesh2d.gpt2_init(hidden=hidden, layers=2, heads=heads,
                                  vocab=vocab, max_seq=seq)
    step, state = pipeline.build_pipeline_step(
        mesh, seg_params, hidden=hidden, heads=heads, microbatches=2,
        mode="overlapped")
    tokens, labels = pipeline.make_batch_3d(mesh, microbatches=2,
                                            seq=seq, vocab=vocab)
    return step, state + (tokens, labels), {}


@functools.lru_cache(maxsize=2)
def _tiny_engine(cache_mode="bf16"):
    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.serving import ServeConfig, ServeEngine
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        compute_dtype=jnp.bfloat16, use_flash_attention=False,
        normalization="rmsnorm", position_embedding_type="rope",
        activation="swiglu", num_query_groups=4, ffn_hidden_size=128)
    model = GPTModel(cfg, decode=True)
    rng = np.random.RandomState(0)
    params = GPTModel(cfg).init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))["params"]
    devices = jax.devices()
    mesh = (Mesh(np.asarray(devices), ("data",))
            if len(devices) > 1 and 8 % len(devices) == 0 else None)
    serve_cfg = ServeConfig(batch_buckets=(2,), prefill_buckets=(8,),
                            num_slots=8, cache_mode=cache_mode,
                            eos_token_id=None, temperature=0.0)
    return ServeEngine(model, params, serve_cfg, mesh=mesh)


def serve_decode_step():
    """The serving hot loop: the engine's own continuous-batching
    decode function at its smallest batch bucket (store donated, the
    poison-slot quarantine handle traced — the exact executable the
    bucket ladder compiles)."""
    engine = _tiny_engine()
    b = engine.config.batch_buckets[0]
    args = (engine._store, engine._params,
            engine._put(np.zeros((b,), np.int32)),
            engine._put(np.zeros((b,), np.int32)),
            jax.random.PRNGKey(0), engine._put(np.int32(-1)))
    fn = jax.jit(engine._decode_fn,
                 donate_argnums=(0,) if engine.config.donate else ())
    return fn, args, {}


@functools.lru_cache(maxsize=1)
def _tiny_engine_tp():
    """The ``serve_decode`` tiny model served tensor-parallel over a
    (data=1, tp=2) mesh slice — the big-model configuration where the
    KV store is sharded on the head axis and the decode body psums
    partial logits over ``tp``. Leaves parallel_state initialized at
    tp=2 (the lowering the caller is about to run needs it)."""
    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.serving import ServeConfig, ServeEngine
    from apex_tpu.transformer import parallel_state

    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        compute_dtype=jnp.bfloat16, use_flash_attention=False,
        normalization="rmsnorm", position_embedding_type="rope",
        activation="swiglu", num_query_groups=4, ffn_hidden_size=128)
    # full-size params FIRST (tp unbound): the engine splits them into
    # per-rank stacks itself — initializing under tp=2 would hand it
    # already-local params and double-split
    parallel_state.destroy_model_parallel()
    rng = np.random.RandomState(0)
    params = GPTModel(cfg).init(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8))))["params"]
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, devices=jax.devices()[:2])
    model = GPTModel(cfg, decode=True)
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(1, 2),
                ("data", "tp"))
    serve_cfg = ServeConfig(batch_buckets=(2,), prefill_buckets=(8,),
                            num_slots=4, eos_token_id=None,
                            temperature=0.0)
    return ServeEngine(model, params, serve_cfg, mesh=mesh)


def serve_decode_tp_step():
    """The TP serving hot loop: the same decode body ``serve_decode``
    lints, wrapped in the engine's jit(shard_map) manual-SPMD ladder
    entry — KV store sharded over ``tp`` at the head axis, stacked
    per-rank params unstacked inside, logits psummed on ``tp``. Lint
    pricing this entry is what keeps the model-axis comm bill honest
    (static == measured on ``tp``)."""
    from apex_tpu.transformer import parallel_state

    engine = _tiny_engine_tp()
    # the cached engine outlives test-harness parallel_state resets;
    # re-tracing its body needs tp=2 rebound exactly as built
    if parallel_state.get_tensor_model_parallel_world_size() != 2:
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, devices=jax.devices()[:2])
    b = engine.config.batch_buckets[0]
    args = (engine._store, engine._params,
            engine._put(np.zeros((b,), np.int32)),
            engine._put(np.zeros((b,), np.int32)),
            jax.random.PRNGKey(0), engine._put(np.int32(-1)))
    fn = jax.jit(engine._tp_decode_body(),
                 donate_argnums=(0,) if engine.config.donate else ())
    return fn, args, {}


# config name -> builder; the CLI's column set and the tier-1
# clean-pass parametrization both read this
TARGETS = {
    "ddp_fp32": ddp_fp32_step,
    "ddp_int8": ddp_int8_step,
    "ddp_overlapped": ddp_overlapped_step,
    "zero": zero_step,
    "guarded": guarded_step,
    "tp_dp": tp_dp_step,
    "tp_dp_overlapped": tp_dp_overlapped_step,
    "pp_tp_dp": pp_tp_dp_step,
    "serve_decode": serve_decode_step,
    "serve_decode_tp": serve_decode_tp_step,
}
