"""The hot-path invariant rules.

Each rule is a pure function ``(ctx, cfg) -> [Finding, ...]`` over a
:class:`~apex_tpu.analysis.lint.LintContext` (lowered StableHLO text,
optionally the closed jaxpr and the concrete example arguments). Rules
never raise on programs they don't understand — an unmatched construct
is "no finding", and a rule whose required artifact is missing from the
context is *skipped* (reported as such in the
:class:`~apex_tpu.analysis.lint.LintReport`), never silently passed.

The catalog (docs/analysis.md has the worked examples):

==========================  ================================================
rule                        catches
==========================  ================================================
``no-host-callback``        ``custom_call`` to a Python host callback (or
                            infeed/outfeed) inside a compiled hot path — a
                            per-step host sync
``no-f64``                  any f64/complex128 tensor in the module — on
                            TPU this means slow emulation and 2x memory
``unexpected-upcast``       a dot/conv executing in f32 whose operands were
                            both upcast from bf16/f16 — the matmul silently
                            left the MXU's fast path
``donation-coverage``       a large carry-state argument (same shape+dtype
                            as an output) accepted but not donated — the
                            2x-HBM footgun
``double-donation``         one buffer appearing at two donated argument
                            positions — XLA's runtime "donate the same
                            buffer twice" INVALID_ARGUMENT, caught at trace
                            time (the amp-O2 aliased-masters bug)
``trace-constant-capture``  a large array baked into the executable as a
                            trace-time constant (closed-over data)
``collective-consistency``  collective sequences that diverge across
                            ``cond``/``switch`` branches, or a collective
                            over an axis the enclosing mesh doesn't bind —
                            deadlock risk on real multi-host
``replication-blowup``      mesh present but a large output/constrained
                            intermediate explicitly replicated — per-device
                            memory scales with global size
``overlap-serialization``   a large reduction collective whose operand
                            transitively depends on ANOTHER large reduction
                            collective's result — a serialized chain the
                            latency-hiding scheduler cannot overlap (the
                            static check that an overlapped step's buckets
                            stay independent)
``implicit-reshard``        a collective-permute / all-to-all in the HLO
                            with no corresponding collective in the source
                            jaxpr — GSPMD resharded behind our back (named
                            by operand and wire bytes)
``replica-group-consistency``  collectives whose replica groups cannot be
                            executed by one SPMD schedule — overlapping
                            groups, groups that miss part of the device
                            set every device is forced through, or unequal
                            group sizes (deadlock shapes on real meshes)
``comm-budget``             static per-program wire bytes exceed the
                            declared budget (``LintConfig.comm_budget_bytes``
                            / ``APEX_TPU_HLO_LINT_COMM_BUDGET``; 0 = off)
``sharding-propagation-loss``  a large intermediate pinned replicated
                            BETWEEN two sharded values — propagation lost
                            the sharding mid-program (the per-edge
                            generalization of ``replication-blowup``)
==========================  ================================================
"""

import dataclasses
import os
from typing import Optional

from apex_tpu.analysis import hlo


@dataclasses.dataclass
class Finding:
    """One structured violation: which rule, what, and where."""

    rule: str
    message: str
    where: str = ""          # op / argument path the finding anchors to
    severity: str = "error"
    extra: Optional[dict] = None

    def to_dict(self):
        d = {"rule": self.rule, "severity": self.severity,
             "message": self.message, "where": self.where}
        if self.extra:
            d.update(self.extra)
        return d

    def __str__(self):
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.rule}{loc}: {self.message}"


def _env_bytes(name, default):
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


@dataclasses.dataclass
class LintConfig:
    """Size thresholds (bytes) the rules key on. The defaults (1 MiB)
    target real models; tests pass smaller ones. Env overrides let a
    capture tighten/loosen a whole run without code changes."""

    donate_min_bytes: int = 1 << 20
    const_min_bytes: int = 1 << 20
    replicated_min_bytes: int = 1 << 20
    overlap_min_bytes: int = 1 << 20
    # static per-program wire-byte budget for the comm-budget rule;
    # 0 = no budget declared (the rule runs and is vacuously clean)
    comm_budget_bytes: int = 0
    max_findings_per_rule: int = 16

    def __post_init__(self):
        self.donate_min_bytes = _env_bytes(
            "APEX_TPU_HLO_LINT_DONATE_BYTES", self.donate_min_bytes)
        self.const_min_bytes = _env_bytes(
            "APEX_TPU_HLO_LINT_CONST_BYTES", self.const_min_bytes)
        self.replicated_min_bytes = _env_bytes(
            "APEX_TPU_HLO_LINT_REPLICATED_BYTES",
            self.replicated_min_bytes)
        self.overlap_min_bytes = _env_bytes(
            "APEX_TPU_HLO_LINT_OVERLAP_BYTES", self.overlap_min_bytes)
        self.comm_budget_bytes = _env_bytes(
            "APEX_TPU_HLO_LINT_COMM_BUDGET", self.comm_budget_bytes)


# custom_call targets that ARE host round-trips. Matched against parsed
# target names (hlo.custom_call_targets), so a stray "callback" in a
# backend_config or comment can never false-positive — and a new jax
# callback target still matches via the substring fallback below.
HOST_CALLBACK_TARGETS = frozenset({
    "xla_python_cpu_callback",
    "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback",
    "xla_ffi_python_gpu_callback",
    "xla_ffi_partitioned_python_cpu_callback",
})
_CALLBACK_MARKERS = ("callback", "io_callback")

# custom_call targets that are COMPILED Pallas kernels, not host
# round-trips: a pallas_call lowers to a custom_call whose payload runs
# entirely on-device (Mosaic on TPU/CPU, Triton on GPU). With the
# apex_tpu.kernels layer these now appear in kernel-backed hot paths,
# and the no-host-callback rule must never flag them — this allowlist
# wins over both the exact host-target set and the substring markers.
# Extendable without a code change via APEX_TPU_HLO_LINT_PALLAS_TARGETS
# (comma-separated target names) for new backends/runtime versions.
PALLAS_CUSTOM_CALL_TARGETS = frozenset({
    "tpu_custom_call",            # Pallas TPU (Mosaic)
    "mosaic_cpu",                 # Pallas CPU
    "mosaic_gpu",
    "triton_kernel_call",         # Pallas GPU (Triton)
    "__gpu$xla.gpu.triton",
})


def _pallas_targets():
    extra = os.environ.get("APEX_TPU_HLO_LINT_PALLAS_TARGETS", "")
    allowed = set(PALLAS_CUSTOM_CALL_TARGETS)
    allowed.update(t.strip() for t in extra.split(",") if t.strip())
    return allowed


def rule_no_host_callback(ctx, cfg):
    findings = []
    pallas = _pallas_targets()
    for target, count in sorted(
            hlo.custom_call_targets(ctx.hlo_text).items()):
        if target in pallas:
            continue  # compiled Pallas kernel — on-device custom_call
        if target in HOST_CALLBACK_TARGETS or any(
                m in target.lower() for m in _CALLBACK_MARKERS):
            findings.append(Finding(
                "no-host-callback",
                f"custom_call to host callback target '{target}' "
                f"({count}x) — every dispatch round-trips to Python",
                where=f"custom_call @{target}"))
    for op in ("stablehlo.infeed", "stablehlo.outfeed"):
        n = ctx.hlo_text.count(op + " ")
        if n:
            findings.append(Finding(
                "no-host-callback",
                f"{op} ({n}x) — host transfer inside the compiled step",
                where=op))
    return findings


def rule_no_f64(ctx, cfg):
    findings = []
    for dtype in ("f64", "complex<f64>"):
        hits = hlo.find_dtype_lines(ctx.hlo_text, dtype)
        if hits:
            line_no, line = hits[0]
            findings.append(Finding(
                "no-f64",
                f"{len(hits)} op(s) with {dtype} tensors (first at "
                f"module line {line_no}: {line[:120]}) — f64 on the "
                f"training step means emulation + 2x memory",
                where=f"line {line_no}",
                extra={"count": len(hits)}))
    return findings


_HALF = ("bfloat16", "float16")
# layout-preserving primitives that carry the "came from half
# precision" taint from a convert to the dot that consumes it
_TAINT_THROUGH = frozenset({
    "transpose", "reshape", "broadcast_in_dim", "squeeze", "copy",
    "slice", "rev",
})


def _eqn_where(eqn):
    """Best-effort source location of a jaxpr equation."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info.traceback)
        if frame is not None:
            return f"{os.path.basename(frame.file_name)}:{frame.line_num}"
    except Exception:
        pass
    return ""


def _iter_subjaxprs(eqn):
    for v in eqn.params.values():
        if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            yield v.jaxpr
        elif hasattr(v, "eqns"):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                    yield item.jaxpr
                elif hasattr(item, "eqns"):
                    yield item


def _is_var(v):
    # jaxpr invars mix Vars with (unhashable) Literals; taint tracking
    # only ever applies to Vars
    return not hasattr(v, "val")


def rule_unexpected_upcast(ctx, cfg):
    if ctx.closed_jaxpr is None:
        return None  # needs the jaxpr — skipped, not passed
    findings = []

    def walk(jaxpr):
        tainted = set()  # vars that are f32 upcasts of half-precision data
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "convert_element_type":
                src = eqn.invars[0]
                src_dtype = str(getattr(getattr(src, "aval", None),
                                        "dtype", ""))
                out_dtype = str(eqn.outvars[0].aval.dtype)
                if src_dtype in _HALF and out_dtype == "float32":
                    tainted.add(eqn.outvars[0])
                elif out_dtype == "float32" and _is_var(src) \
                        and src in tainted:
                    tainted.add(eqn.outvars[0])
            elif name in _TAINT_THROUGH:
                if any(_is_var(v) and v in tainted for v in eqn.invars):
                    tainted.update(eqn.outvars)
            elif name in ("dot_general", "conv_general_dilated"):
                operands = [v for v in eqn.invars if hasattr(v, "aval")
                            and getattr(v.aval, "shape", None) is not None]
                out_dtype = str(eqn.outvars[0].aval.dtype)
                if (out_dtype == "float32" and len(operands) >= 2
                        and all(_is_var(v) and v in tainted
                                for v in operands[:2])):
                    shapes = "x".join(
                        str(list(v.aval.shape)) for v in operands[:2])
                    findings.append(Finding(
                        "unexpected-upcast",
                        f"{name} executes in f32 but both operands were "
                        f"upcast from half precision ({shapes}) — run it "
                        f"in bf16 (use preferred_element_type=f32 if f32 "
                        f"accumulation was the goal)",
                        where=_eqn_where(eqn) or name))
            for sub in _iter_subjaxprs(eqn):
                walk(sub)

    walk(ctx.closed_jaxpr.jaxpr)
    return findings


def _fmt_bytes(n):
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"


_HLO_TO_NP_DTYPE = {
    "f64": "float64", "f32": "float32", "f16": "float16",
    "bf16": "bfloat16", "i64": "int64", "i32": "int32", "i16": "int16",
    "i8": "int8", "i1": "bool", "ui64": "uint64", "ui32": "uint32",
    "ui16": "uint16", "ui8": "uint8",
}


def _arg_aval(info):
    aval = getattr(info, "aval", None)
    return aval if aval is not None else getattr(info, "_aval", None)


def rule_donation_coverage(ctx, cfg):
    args = ctx.flat_args_info
    if args is None:
        return None
    # multiset of result (shape, dtype) signatures; donated args'
    # matching outputs are consumed first (they already carry state)
    out_sigs = {}
    if ctx.out_avals is not None:
        for o in ctx.out_avals:
            key = (tuple(o.shape), str(o.dtype))
            out_sigs[key] = out_sigs.get(key, 0) + 1
    else:
        for r in hlo.entry_signature(ctx.hlo_text)["results"]:
            if r["shape"] is None:
                continue
            key = (r["shape"], _HLO_TO_NP_DTYPE.get(r["dtype"],
                                                    r["dtype"]))
            out_sigs[key] = out_sigs.get(key, 0) + 1

    def aval_key(aval):
        return (tuple(aval.shape), str(getattr(aval, "dtype", "")))

    findings = []
    for key in (aval_key(_arg_aval(a)) for _, a in args
                if a.donated):
        if out_sigs.get(key, 0) > 0:
            out_sigs[key] -= 1
    for path, info in args:
        if info.donated:
            continue
        aval = _arg_aval(info)
        nbytes = getattr(aval, "size", 0) * getattr(
            getattr(aval, "dtype", None), "itemsize", 4)
        if nbytes < cfg.donate_min_bytes:
            continue
        key = aval_key(aval)
        if out_sigs.get(key, 0) > 0:
            out_sigs[key] -= 1
            findings.append(Finding(
                "donation-coverage",
                f"carry-state argument '{path}' "
                f"({key[1]}{list(key[0])}, {_fmt_bytes(nbytes)}) is "
                f"returned with identical shape+dtype but not donated — "
                f"XLA must keep both copies live (2x HBM for this "
                f"buffer); add it to donate_argnums",
                where=path,
                extra={"nbytes": nbytes}))
    return findings


def rule_double_donation(ctx, cfg):
    if ctx.flat_args is None or ctx.flat_args_info is None:
        return None
    by_buffer = {}
    for (path, info), (_, value) in zip(ctx.flat_args_info,
                                        ctx.flat_args):
        if not info.donated or value is None:
            continue
        keys = [("id", id(value))]
        try:
            keys.append(("ptr", value.unsafe_buffer_pointer()))
        except Exception:
            pass
        for key in keys:
            by_buffer.setdefault(key, []).append(path)
    findings = []
    seen = set()
    for key, paths in by_buffer.items():
        unique = sorted(set(paths))
        if len(unique) < 2 or tuple(unique) in seen:
            continue
        seen.add(tuple(unique))
        findings.append(Finding(
            "double-donation",
            f"the same buffer is donated at {len(unique)} argument "
            f"positions ({', '.join(unique)}) — XLA raises 'Attempt to "
            f"donate the same buffer twice' at Execute(); make the "
            f"copies distinct (see optimizers._base.master_copy_tree)",
            where=unique[0],
            extra={"paths": unique}))
    return findings


def rule_trace_constant_capture(ctx, cfg):
    findings = []
    if ctx.closed_jaxpr is not None:
        for i, const in enumerate(ctx.closed_jaxpr.consts):
            shape = getattr(const, "shape", None)
            dtype = getattr(const, "dtype", None)
            if shape is None:
                continue
            size = 1
            for d in shape:
                size *= int(d)
            nbytes = size * getattr(dtype, "itemsize", 4)
            if nbytes >= cfg.const_min_bytes:
                findings.append(Finding(
                    "trace-constant-capture",
                    f"trace-time constant #{i} ({dtype}{list(shape)}, "
                    f"{_fmt_bytes(nbytes)}) is baked into the "
                    f"executable — closed-over array data retraces on "
                    f"every new value and bloats the program; pass it "
                    f"as an argument",
                    where=f"const[{i}]",
                    extra={"nbytes": nbytes}))
        return findings
    # text-only fallback (lint_lowered without a jaxpr)
    for line_no, nbytes, spec in hlo.large_constant_bytes(
            ctx.hlo_text, cfg.const_min_bytes):
        findings.append(Finding(
            "trace-constant-capture",
            f"constant tensor<{spec}> ({_fmt_bytes(nbytes)}) baked into "
            f"the module at line {line_no} — pass it as an argument",
            where=f"line {line_no}",
            extra={"nbytes": nbytes}))
    return findings


_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "all_gather", "reduce_scatter",
    "psum_scatter", "all_to_all", "ppermute", "pbroadcast",
    "reduce_precision_psum",
})


def _collective_axes(eqn):
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, (str, int)))


def _collective_signature(jaxpr, acc):
    """Ordered tuple of (primitive, axes) for every collective reachable
    from ``jaxpr`` (recursing into sub-jaxprs in order)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVES:
            acc.append((eqn.primitive.name, _collective_axes(eqn)))
        for sub in _iter_subjaxprs(eqn):
            _collective_signature(sub, acc)
    return acc


def rule_collective_consistency(ctx, cfg):
    if ctx.closed_jaxpr is None:
        return None
    findings = []

    def walk(jaxpr, bound_axes):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _COLLECTIVES:
                for ax in _collective_axes(eqn):
                    if isinstance(ax, str) and bound_axes is not None \
                            and ax not in bound_axes:
                        findings.append(Finding(
                            "collective-consistency",
                            f"{name} over axis '{ax}' but the enclosing "
                            f"mesh binds only {sorted(bound_axes)} — "
                            f"this lowers to a collective a sibling "
                            f"host will never enter (deadlock on real "
                            f"multi-host)",
                            where=_eqn_where(eqn) or name))
            if name == "cond":
                branches = eqn.params.get("branches", ())
                sigs = [tuple(_collective_signature(b.jaxpr, []))
                        for b in branches]
                if len(set(sigs)) > 1 and any(sigs):
                    desc = " vs ".join(
                        "[" + ", ".join(
                            f"{p}{list(a)}" for p, a in s) + "]"
                        for s in sigs)
                    findings.append(Finding(
                        "collective-consistency",
                        f"cond branches issue different collective "
                        f"sequences ({desc}) — replicas taking "
                        f"different branches deadlock; hoist the "
                        f"collectives out of the branch or make the "
                        f"sequences identical",
                        where=_eqn_where(eqn) or "cond"))
            if name == "while":
                body = eqn.params.get("body_jaxpr")
                sig = (tuple(_collective_signature(body.jaxpr, []))
                       if body is not None else ())
                if sig:
                    desc = ", ".join(f"{p}{list(a)}" for p, a in sig)
                    findings.append(Finding(
                        "collective-consistency",
                        f"collective(s) inside a data-dependent while "
                        f"loop ({desc}) — replicas whose predicates "
                        f"disagree run different collective counts and "
                        f"deadlock; use a fixed-trip scan or hoist the "
                        f"collective",
                        where=_eqn_where(eqn) or "while"))
            new_bound = bound_axes
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                axis_names = getattr(mesh, "axis_names", None)
                if axis_names is not None:
                    new_bound = (set(axis_names)
                                 | (bound_axes or set()))
            for sub in _iter_subjaxprs(eqn):
                walk(sub, new_bound)

    walk(ctx.closed_jaxpr.jaxpr, None)
    return findings


def rule_replication_blowup(ctx, cfg):
    text = ctx.hlo_text
    if hlo.num_partitions(text) <= 1:
        return []  # no mesh in play: replication is the only layout
    findings = []
    sig = hlo.entry_signature(text)
    for i, r in enumerate(sig["results"]):
        if r["sharding"] == "{replicated}" \
                and r["nbytes"] >= cfg.replicated_min_bytes:
            findings.append(Finding(
                "replication-blowup",
                f"output #{i} (tensor<{r['type']}>, "
                f"{_fmt_bytes(r['nbytes'])}) is explicitly replicated "
                f"across a {hlo.num_partitions(text)}-partition mesh — "
                f"every device holds the full buffer; shard it or "
                f"confirm the replication is intended",
                where=f"result[{i}]",
                extra={"nbytes": r["nbytes"]}))
    for line_no, sharding, spec in hlo.sharding_custom_calls(text):
        if sharding != "{replicated}":
            continue
        _, _, nbytes = hlo.parse_tensor_type(spec)
        if nbytes >= cfg.replicated_min_bytes:
            findings.append(Finding(
                "replication-blowup",
                f"sharding constraint pins tensor<{spec}> "
                f"({_fmt_bytes(nbytes)}) fully replicated at module "
                f"line {line_no} — a large intermediate holds one full "
                f"copy per device",
                where=f"line {line_no}",
                extra={"nbytes": nbytes}))
    return findings


# Reduction collectives an overlapped schedule must keep independent.
# all_gather is deliberately EXCLUDED: the ZeRO param gather depends on
# the shard update, which depends on the scatter — a legitimate
# pipeline stage, not a serialization bug.
_REDUCTION_COLLECTIVES = frozenset({
    "psum", "psum_scatter", "reduce_scatter", "pmax", "pmin",
    "reduce_precision_psum",
})


def _collective_payload_bytes(eqn):
    """Bytes of the first array operand — the collective's payload."""
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is not None:
            size = 1
            for d in shape:
                size *= int(d)
            return size * getattr(getattr(aval, "dtype", None),
                                  "itemsize", 4)
    return 0


def rule_overlap_serialization(ctx, cfg):
    """Flag a large reduction collective whose operand transitively
    depends on the RESULT of another large reduction collective: the
    downstream collective cannot start until the upstream one
    completes, so the pair degenerates to a serial chain no
    latency-hiding scheduler can overlap with compute (the
    all-collectives-in-one-trailing-block failure mode the overlapped
    step exists to avoid; parallel/overlap.py emits every bucket's
    collective with NO cross-bucket dependence, and this rule is the
    static proof it stays that way). Small collectives — the scalar
    guard-flag psum, the int8 per-block scale pmax that feeds its OWN
    bucket's payload — sit below ``overlap_min_bytes`` and neither
    taint nor trip. ``optimization_barrier`` joins propagate dependence
    like any other op, so a barrier between buckets is caught too."""
    if ctx.closed_jaxpr is None:
        return None
    findings = []
    counter = [0]

    def walk(jaxpr, taint):
        """``taint``: var -> frozenset of upstream big-collective ids.
        Returns the ids minted inside this jaxpr (for the parent eqn's
        outputs)."""
        minted = set()
        for eqn in jaxpr.eqns:
            in_taint = set()
            for v in eqn.invars:
                if _is_var(v):
                    in_taint |= taint.get(v, frozenset())
            name = eqn.primitive.name
            big = (name in _REDUCTION_COLLECTIVES
                   and _collective_payload_bytes(eqn)
                   >= cfg.overlap_min_bytes)
            out_taint = set(in_taint)
            if big:
                if in_taint:
                    findings.append(Finding(
                        "overlap-serialization",
                        f"{name} (payload "
                        f"{_fmt_bytes(_collective_payload_bytes(eqn))}) "
                        f"input depends on the result of "
                        f"{len(in_taint)} earlier large reduction "
                        f"collective(s) — the chain serializes them "
                        f"into one block XLA cannot overlap with "
                        f"compute; emit per-bucket collectives with "
                        f"independent operands (see "
                        f"parallel/overlap.py)",
                        where=_eqn_where(eqn) or name,
                        extra={"upstream": len(in_taint)}))
                cid = counter[0]
                counter[0] += 1
                out_taint.add(cid)
                minted.add(cid)
            for sub in _iter_subjaxprs(eqn):
                sub_taint = {v: frozenset(in_taint)
                             for v in sub.invars if _is_var(v)}
                inner = walk(sub, sub_taint)
                out_taint |= inner
                minted |= inner
            frozen = frozenset(out_taint)
            for v in eqn.outvars:
                taint[v] = frozen
        return minted

    walk(ctx.closed_jaxpr.jaxpr, {})
    return findings


# ---------------------------------------------------------------------------
# the SPMD communication rules (analysis/sharding.py — the collective
# dataflow graph is parsed once per context and shared)
# ---------------------------------------------------------------------------

def _op_where(op):
    return f"{op.kind}@line {op.lineno}"


def rule_implicit_reshard(ctx, cfg):
    """A collective-permute / all-to-all in the HLO with no
    corresponding collective in the source jaxpr: GSPMD (or the SPMD
    partitioner) inserted a reshard the author never wrote. On the
    trace-only ``lint_fn`` path text and jaxpr agree 1:1, so this is
    clean by construction; the finding fires on contexts built from
    post-partitioning HLO (``sharding.audit_spmd``) or hand-supplied
    text — exactly where the silent insertion is visible."""
    if ctx.closed_jaxpr is None:
        return None  # nothing to compare against — skipped, not passed
    from apex_tpu.analysis import sharding

    graph = sharding.graph_for_context(ctx)
    authored = sharding.jaxpr_collective_counts(ctx.closed_jaxpr.jaxpr)
    findings = []
    for kind in ("collective_permute", "all_to_all"):
        emitted = [op for op in graph.ops if op.kind == kind]
        extra = len(emitted) - authored.get(kind, 0)
        if extra <= 0:
            continue
        # the ops beyond the authored count, in module order, are the
        # insertions — name each by its operand and wire bytes
        for op in emitted[len(emitted) - extra:]:
            operand = op.operands[0] if op.operands else "<?>"
            shape, dtype, _ = (op.operand_specs[0] if op.operand_specs
                               else (None, "?", 0))
            findings.append(Finding(
                "implicit-reshard",
                f"{kind} over operand {operand} "
                f"({dtype}{list(shape) if shape else '?'}, "
                f"{_fmt_bytes(op.wire_bytes)} on the wire) has no "
                f"corresponding collective in the source jaxpr — the "
                f"partitioner resharded behind your back; make the "
                f"layout transition explicit (with_sharding_constraint "
                f"/ shard_map) or fix the producer/consumer shardings "
                f"to agree",
                where=_op_where(op),
                extra={"nbytes": op.wire_bytes, "operand": operand}))
    return findings


def rule_replica_group_consistency(ctx, cfg):
    """Replica-group partitions every device can actually execute in
    one SPMD schedule: in SPMD every device runs every collective in
    program order, so each op's groups must tile the SAME device set —
    a device appearing in two groups, a device the groups miss, or
    unequal group sizes is a shape XLA either rejects at runtime or,
    worse, deadlocks on across hosts."""
    from apex_tpu.analysis import sharding

    graph = sharding.graph_for_context(ctx)
    if not graph.ops:
        return []
    universe = graph.device_set()
    findings = []
    for op in graph.ops:
        if op.replica_groups is not None:
            flat = [d for g in op.replica_groups for d in g]
            dupes = sorted({d for d in flat if flat.count(d) > 1})
            if dupes:
                findings.append(Finding(
                    "replica-group-consistency",
                    f"{op.kind} replica groups list device(s) {dupes} "
                    f"in more than one group — not a partition; no "
                    f"SPMD schedule can execute it",
                    where=_op_where(op)))
                continue
            missing = sorted(universe - set(flat))
            if missing:
                findings.append(Finding(
                    "replica-group-consistency",
                    f"{op.kind} replica groups cover only "
                    f"{sorted(set(flat))} of the program's device set "
                    f"— device(s) {missing} execute the op with no "
                    f"group to join (deadlock on real multi-host)",
                    where=_op_where(op),
                    extra={"missing": missing}))
            sizes = {len(g) for g in op.replica_groups}
            if len(sizes) > 1:
                findings.append(Finding(
                    "replica-group-consistency",
                    f"{op.kind} replica groups have unequal sizes "
                    f"{sorted(sizes)} — XLA requires a uniform "
                    f"partition of the device set",
                    where=_op_where(op)))
        if op.source_target_pairs is not None:
            targets = [p[-1] for p in op.source_target_pairs if p]
            dup_t = sorted({t for t in targets if targets.count(t) > 1})
            if dup_t:
                findings.append(Finding(
                    "replica-group-consistency",
                    f"{op.kind} source_target_pairs send to device(s) "
                    f"{dup_t} more than once — conflicting writes, "
                    f"rejected at execution",
                    where=_op_where(op)))
            out_of_range = sorted({d for p in op.source_target_pairs
                                   for d in p if d not in universe})
            if out_of_range and universe:
                findings.append(Finding(
                    "replica-group-consistency",
                    f"{op.kind} source_target_pairs reference "
                    f"device(s) {out_of_range} outside the program's "
                    f"device set {sorted(universe)}",
                    where=_op_where(op)))
    return findings


def rule_comm_budget(ctx, cfg):
    """Static per-program wire bytes vs the declared budget. With no
    budget declared (``comm_budget_bytes == 0``) the rule runs and is
    vacuously clean — declare one per capture via
    ``APEX_TPU_HLO_LINT_COMM_BUDGET`` or ``LintConfig``."""
    if cfg.comm_budget_bytes <= 0:
        return []
    from apex_tpu.analysis import sharding

    graph = sharding.graph_for_context(ctx)
    total = graph.total_wire_bytes
    if total <= cfg.comm_budget_bytes:
        return []
    top = max(graph.ops, key=lambda op: op.wire_bytes)
    return [Finding(
        "comm-budget",
        f"static program wire bytes {_fmt_bytes(total)} exceed the "
        f"declared budget {_fmt_bytes(cfg.comm_budget_bytes)} "
        f"(largest contributor: {top.kind} at line {top.lineno}, "
        f"{_fmt_bytes(top.wire_bytes)}) — compress the payload, shard "
        f"the state, or raise the budget deliberately",
        where=_op_where(top),
        extra={"nbytes": total,
               "budget_bytes": cfg.comm_budget_bytes})]


def rule_sharding_propagation_loss(ctx, cfg):
    """A large intermediate pinned ``{replicated}`` between two sharded
    values: sharding propagation lost the layout mid-program, so every
    device holds (and every boundary moves) the full buffer on an edge
    whose endpoints are sharded — the per-edge generalization of
    ``replication-blowup`` (which flags the replicated tensor itself;
    this rule fires only when the surrounding dataflow proves the
    replication is a LOSS, naming both sharded endpoints)."""
    text = ctx.hlo_text
    if hlo.num_partitions(text) <= 1:
        return []
    from apex_tpu.analysis import sharding

    graph = sharding.graph_for_context(ctx).value_graph
    lines = text.splitlines()
    # sharded evidence: entry args and @Sharding constraints whose
    # annotation is a real partition (not replicated / manual)
    def _is_sharded(annot):
        return annot is not None and "replicated" not in annot \
            and "manual" not in annot

    sharded_vars = {}
    # entry args with a real partition annotation are sharded roots;
    # the arg lines sit inside @main in every lowering jax produces
    for i, arg, annot in hlo.arg_shardings(text):
        if _is_sharded(annot):
            sharded_vars[sharding._qual("main", arg)] = \
                f"{arg} (entry arg, line {i})"
    func = ""
    for i, line in enumerate(lines, 1):
        fm = sharding._FUNC_RE.search(line)
        if fm:
            func = fm.group(1)
        if "custom_call @Sharding" in line:
            om = hlo._SHARDING_OP_RE.search(line)
            sm = hlo._SHARDING_ATTR_RE.search(line)
            if om is not None and sm is not None \
                    and _is_sharded(sm.group(1)):
                sharded_vars[sharding._qual(func, om.group(1))] = \
                    f"sharding constraint at line {i}"
    if not sharded_vars:
        return []
    findings = []
    constraint_lines = {i for i, _, _ in hlo.sharding_custom_calls(text)}
    func = ""
    for i, line in enumerate(lines, 1):
        fm = sharding._FUNC_RE.search(line)
        if fm:
            func = fm.group(1)
        if i not in constraint_lines:
            continue
        sm = hlo._SHARDING_ATTR_RE.search(line)
        if sm is None or sm.group(1) != "{replicated}":
            continue
        tensors = hlo._TENSOR_RE.findall(line)
        if not tensors:
            continue
        _, _, nbytes = hlo.parse_tensor_type(tensors[-1])
        if nbytes < cfg.replicated_min_bytes:
            continue
        om = hlo._SHARDING_OP_RE.search(line)
        if om is None:
            continue
        result = sharding._qual(func, om.group(1))
        up = graph.ancestors(result) & set(sharded_vars)
        down = graph.descendants(result) & set(sharded_vars)
        if up and down:
            src = sharded_vars[sorted(up)[0]]
            dst = sharded_vars[sorted(down)[0]]
            findings.append(Finding(
                "sharding-propagation-loss",
                f"tensor<{tensors[-1]}> ({_fmt_bytes(nbytes)}) is "
                f"carried fully replicated at line {i} between sharded "
                f"values (upstream: {src}; downstream: {dst}) — "
                f"propagation lost the sharding mid-program; constrain "
                f"this intermediate to a sharded layout",
                where=f"line {i}",
                extra={"nbytes": nbytes}))
    return findings


# rule registry: name -> (fn, what it needs beyond the HLO text).
# Order is the report order.
RULES = {
    "no-host-callback": (rule_no_host_callback, ()),
    "no-f64": (rule_no_f64, ()),
    "unexpected-upcast": (rule_unexpected_upcast, ("jaxpr",)),
    "donation-coverage": (rule_donation_coverage, ("args_info",)),
    "double-donation": (rule_double_donation, ("args",)),
    "trace-constant-capture": (rule_trace_constant_capture, ()),
    "collective-consistency": (rule_collective_consistency, ("jaxpr",)),
    "overlap-serialization": (rule_overlap_serialization, ("jaxpr",)),
    "replication-blowup": (rule_replication_blowup, ()),
    "implicit-reshard": (rule_implicit_reshard, ("jaxpr",)),
    "replica-group-consistency": (rule_replica_group_consistency, ()),
    "comm-budget": (rule_comm_budget, ()),
    "sharding-propagation-loss": (rule_sharding_propagation_loss, ()),
}
