"""Overlapped backward/collective training step.

Why: every DP sync path in this repo runs the FULL backward and then
reduces every gradient bucket (``all_reduce_gradients_bucketed``), so
communication time adds serially to compute time. T3 (arXiv 2401.16677)
and the fused computation-collective work (arXiv 2305.06942) show that
launching each bucket's reduction as soon as its gradients are ready
hides most of the comm latency behind the rest of the backward; the
cross-replica weight-update sharding scheme (arXiv 2004.13336) extends
the same idea to ZeRO — interleave each bucket's sharded optimizer
update with its reduce-scatter.

Design: the caller splits the model into K layer-group *segments* —
``segments[k](params_k, carry) -> carry`` with the LAST segment
returning the scalar loss — and :class:`OverlappedDataParallel` runs
the forward through the chain capturing per-segment ``jax.vjp``
closures, then walks the backward segment-by-segment IN REVERSE,
emitting each ready bucket's collective (int8 / bf16 / fp32, planned
with the same dtype-segregated ``plan_buckets`` the bucketed allreduce
uses) before the earlier segments' backward is even traced. The
resulting dataflow has an explicit dependency structure with NO barrier
between buckets: bucket *i*'s psum depends only on segment *i*'s
cotangents, never on segments that run after it, so XLA's
latency-hiding scheduler is free to interleave the collectives with the
remaining backward compute. The bucketed baseline cannot offer that:
its ``message_size`` buckets span layer boundaries in FORWARD order, so
a bucket only becomes ready when its earliest layer's gradient — the
LAST one the backward produces — lands, which degenerates to "all
collectives in one trailing block" (the ``overlap-serialization`` lint
rule in apex_tpu.analysis is the static check that the overlapped step
never regresses to that shape).

Two perf mechanisms, stated honestly (docs/parallelism.md has the
measured numbers):

- on real multi-core/TPU backends the win is latency hiding — the
  collectives execute concurrently with the backward;
- on the 1-core CPU mesh this repo measures on, nothing runs
  concurrently, so the win comes from eliminated work: the
  error-feedback residual lives in the quantization block domain
  (``[nblocks, block]``) as persistent carry state — no per-step
  ``flatten``/``unflatten`` marshalling of a full-model fp32 tree — and
  ``fold_average=True`` folds the ``1/world`` gradient averaging into
  the per-block dequant scales (a ``[nblocks, 1]`` multiply instead of
  a full-length divide pass).

Telemetry: each backward segment opens a ``ddp_overlap_segment_<k>``
span and each emitted bucket a ``ddp_overlap_bucket_<n>`` span, so the
JSONL event stream shows the interleaved emission order (segment K-1,
its buckets, segment K-2, ...) — ``tools/telemetry_report.py``'s
``overlap`` kind renders it as a timeline. Spans around traced code
measure trace time by design (telemetry/trace.py); the measured
``comm_hidden_pct`` comes from the bench's step-time decomposition, not
from the spans.

Composition: the guard (``resilience.guarded_update``) keeps working —
pass ``guard_flag=True`` and the LOCAL pre-compression non-finite flag
is returned for the one scalar psum; the bucket-domain residual reverts
wholesale on a skipped step like any other state pytree. ``numerics=``
appends the same ``grads/*`` + ``synced/*`` stats dict the DDP knob
produces. The step stays one compile under ``assert_no_recompiles`` —
planning is host-side and deterministic in the shapes.
"""

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import compression
from apex_tpu.parallel.distributed import flatten, plan_buckets, unflatten
from apex_tpu.telemetry import comm as _telemetry_comm
from apex_tpu.telemetry import numerics as _numerics
from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.telemetry.registry import get_registry


class Bucket(NamedTuple):
    """One planned bucket: which leaves of its segment it coalesces
    (``plan_buckets`` indices), the flat element count, and the int8
    block-grid row count."""

    leaf_idx: tuple
    n: int
    nblocks: int


def plan_overlap(segment_params: Sequence[Any], *,
                 message_size: int = 10000000,
                 block_size: int = compression.BLOCK_SIZE):
    """Host-side bucket plan: per segment, the same dtype-segregated
    ``message_size``-capped grouping ``all_reduce_gradients_bucketed``
    uses — but never spanning a segment boundary, so every bucket is
    ready the moment its own segment's backward finishes. Returns a
    tuple (per segment) of tuples of :class:`Bucket`."""
    plan = []
    for params in segment_params:
        leaves = jax.tree_util.tree_leaves(params)
        buckets = []
        if leaves:
            for idxs in plan_buckets(leaves, message_size):
                n = int(sum(int(leaves[i].size) for i in idxs))
                buckets.append(Bucket(tuple(idxs), n,
                                      compression.num_blocks(n, block_size)))
        plan.append(tuple(buckets))
    return tuple(plan)


class OverlappedDataParallel:
    """DDP gradient sync restructured for backward/collective overlap.

    Mirrors :class:`~apex_tpu.parallel.DistributedDataParallel`'s
    reduction policy knobs (``gradient_average``,
    ``gradient_predivide_factor``, ``compress``, ``message_size``,
    ``numerics``) but consumes a SEGMENTED model instead of a grad
    pytree: ``value_and_sync`` runs forward + backward itself so it can
    emit each bucket's collective mid-backward.

    ``fold_average=True`` (default) folds the ``1/world`` averaging into
    the int8 dequant scales — fastest, one fp32 rounding per element vs
    the baseline's divide-after order. Pass ``False`` for results
    bit-identical to ``all_reduce_gradients_bucketed`` whenever the
    bucket boundaries land on quantization-block boundaries (leaf sizes
    multiples of ``compress_block_size``); ragged boundaries shift the
    block grid, bounded by the documented per-block quantization error
    either way.

    ``guard_flag=True`` additionally returns the non-finite flag of the
    LOCAL pre-compression gradients (an int8 psum can launder a
    replica's NaN into finite wire garbage — same reasoning as
    ``resilience.guarded_update``), ready for the guard's scalar psum.
    """

    def __init__(self, axis_name="dp", message_size: int = 10000000,
                 compress: Optional[str] = None,
                 compress_block_size: int = compression.BLOCK_SIZE,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 fold_average: bool = True,
                 guard_flag: bool = False,
                 numerics=None):
        if compress not in (None, "bf16", "int8", "int4"):
            raise ValueError(f"unknown compression mode {compress!r}")
        self.axis_name = axis_name
        self.message_size = message_size
        self.compress = compress
        self.compress_block_size = compress_block_size
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.fold_average = fold_average
        self.guard_flag = guard_flag
        self.numerics = numerics

    # -- planning / state ------------------------------------------------

    def plan(self, segment_params):
        return plan_overlap(segment_params,
                            message_size=self.message_size,
                            block_size=self.compress_block_size)

    def init_residual(self, segment_params):
        """Zero error-feedback state for ``compress="int8"``/``"int4"`` — a tuple
        (per segment) of tuples of ``[nblocks, block]`` fp32 zeros, the
        PERSISTENT bucket-domain layout (donate it through the step;
        no per-step flatten/unflatten of a leaf-shaped tree)."""
        bs = self.compress_block_size
        return tuple(
            tuple(jnp.zeros((b.nblocks, bs), jnp.float32) for b in seg)
            for seg in self.plan(segment_params))

    def residual_to_tree(self, segment_params, residual):
        """Bucket-domain residual -> leaf-shaped pytrees (one per
        segment), zero pad tails stripped — the layout the non-overlap
        paths carry, for parity checks and post-mortems."""
        plan = self.plan(segment_params)
        out = []
        for params, seg_plan, seg_res in zip(segment_params, plan,
                                             residual):
            leaves, treedef = jax.tree_util.tree_flatten(params)
            res_leaves = [None] * len(leaves)
            for bucket, r2d in zip(seg_plan, seg_res):
                flat = r2d.reshape(-1)[:bucket.n]
                for i, piece in zip(
                        bucket.leaf_idx,
                        unflatten(flat, [leaves[i]
                                         for i in bucket.leaf_idx])):
                    res_leaves[i] = piece
            out.append(jax.tree_util.tree_unflatten(treedef, res_leaves))
        return out

    # -- the per-bucket collective --------------------------------------

    def _avg_divisor(self):
        if not self.gradient_average:
            return None
        world = lax.axis_size(self.axis_name) \
            if not isinstance(self.axis_name, (tuple, list)) else None
        if world is None:
            world = 1
            for a in self.axis_name:
                world *= lax.axis_size(a)
        return world / self.gradient_predivide_factor

    def _sync_flat(self, flat, res2d):
        """One bucket's collective. Returns ``(synced flat fp32,
        new_residual2d or None)`` — averaging policy applied, matching
        ``_psum_with_policy``'s order of operations unless
        ``fold_average`` moved the divide into the scales."""
        orig_dtype = flat.dtype
        if self.gradient_predivide_factor != 1.0:
            flat = flat / self.gradient_predivide_factor
        divisor = self._avg_divisor()
        if compression.needs_residual(self.compress):
            x2d = compression.pad_to_blocks(flat, self.compress_block_size)
            if res2d is not None:
                x2d = x2d + res2d
            if self.fold_average and divisor is not None:
                out, err = compression.psum_compressed_blocks(
                    x2d, self.axis_name, scale_mult=1.0 / divisor)
                out = out[:flat.shape[0]]
            else:
                out, err = compression.psum_compressed_blocks(
                    x2d, self.axis_name)
                out = out[:flat.shape[0]]
                if divisor is not None:
                    out = out / divisor
            return out.astype(orig_dtype), err
        if self.compress == "bf16":
            _telemetry_comm.record_collective(
                "psum", elements=flat.size, dtype=jnp.bfloat16,
                axis_name=self.axis_name, mode="bf16")
            out = lax.psum(flat.astype(jnp.bfloat16),
                           self.axis_name).astype(flat.dtype)
        else:
            _telemetry_comm.record_collective(
                "psum", elements=flat.size, dtype=flat.dtype,
                axis_name=self.axis_name)
            out = lax.psum(flat, self.axis_name)
        if divisor is not None:
            out = out / divisor
        return out.astype(orig_dtype), None

    # -- the overlapped step --------------------------------------------

    def value_and_sync(self, segments: Sequence[Callable],
                       segment_params: Sequence[Any], x,
                       residual=None):
        """Forward through the segment chain, then segment-by-segment
        backward with each ready bucket's collective emitted before the
        earlier segments' backward.

        ``segments[k](params_k, carry) -> carry``; the last segment
        must return the scalar loss (close over labels/targets — they
        are part of the same trace). Top-level leaf names should be
        unique ACROSS segments when ``numerics`` grouping is on.

        Returns, in order: ``loss``, ``synced`` (list of per-segment
        grad pytrees, averaging policy applied), then ``new_residual``
        (bucket-domain, iff the compress mode carries a residual), then the local
        non-finite ``flag`` (iff ``guard_flag``), then the ``stats``
        dict (iff ``numerics``).
        """
        if len(segments) != len(segment_params):
            raise ValueError(
                f"{len(segments)} segment fns vs {len(segment_params)} "
                f"param groups")
        K = len(segments)
        plan = self.plan(segment_params)
        reg = get_registry()
        if reg.enabled:
            reg.event("overlap", "plan", segments=K,
                      buckets=[len(s) for s in plan],
                      compress=self.compress or "none",
                      fold_average=bool(self.fold_average))
        is_int8 = compression.needs_residual(self.compress)
        if is_int8 and residual is None:
            residual = self.init_residual(segment_params)

        carry = x
        vjps = []
        for k in range(K):
            carry, vjp = jax.vjp(segments[k], segment_params[k], carry)
            vjps.append(vjp)
        loss = carry
        if jnp.shape(loss) != ():
            raise ValueError(
                f"the last segment must return a scalar loss, got shape "
                f"{jnp.shape(loss)}")

        synced = [None] * K
        new_res = [None] * K
        local = [None] * K
        ct = jnp.ones_like(loss)
        seq = 0
        bucket_no = sum(len(s) for s in plan)
        for k in reversed(range(K)):
            with _telemetry_trace.span(f"ddp_overlap_segment_{k}",
                                       role="segment", segment=k,
                                       seq=seq):
                gk, ct = vjps[k](ct)
            seq += 1
            local[k] = gk
            leaves, treedef = jax.tree_util.tree_flatten(gk)
            out_leaves = list(leaves)
            seg_res = []
            # buckets numbered in EMISSION order: the last segment's
            # buckets launch first, so walk the global counter backwards
            bucket_no -= len(plan[k])
            for bi, bucket in enumerate(plan[k]):
                n = bucket_no + bi
                with _telemetry_trace.span(f"ddp_overlap_bucket_{n}",
                                           role="bucket", segment=k,
                                           seq=seq,
                                           elements=bucket.n):
                    flat = flatten([leaves[i] for i in bucket.leaf_idx])
                    r2d = residual[k][bi] if is_int8 else None
                    out, err2d = self._sync_flat(flat, r2d)
                    for i, piece in zip(
                            bucket.leaf_idx,
                            unflatten(out, [leaves[i]
                                            for i in bucket.leaf_idx])):
                        out_leaves[i] = piece
                    seg_res.append(err2d)
                seq += 1
            synced[k] = jax.tree_util.tree_unflatten(treedef, out_leaves)
            new_res[k] = tuple(seg_res)

        outs = (loss, synced)
        if is_int8:
            outs = outs + (tuple(new_res),)
        if self.guard_flag:
            from apex_tpu.resilience.guard import nonfinite_flag

            outs = outs + (nonfinite_flag(local),)
        if self.numerics:
            depth = (_numerics.default_prefix_depth()
                     if self.numerics is True else int(self.numerics))
            stats = {}
            for k in range(K):
                stats.update(_numerics.tree_stats(
                    local[k], prefix_depth=depth, prefix="grads"))
                stats.update(_numerics.tree_stats(
                    synced[k], prefix_depth=depth, prefix="synced"))
            outs = outs + (stats,)
        return outs


# ---------------------------------------------------------------------------
# ZeRO: per-bucket reduce-scatter interleaved with the shard update
# ---------------------------------------------------------------------------

def overlapped_zero_step(segments: Sequence[Callable],
                         segment_params: Sequence[Any], opt, state, x, *,
                         lr=None, found_inf=None, scale: float = 1.0):
    """The ZeRO analog of :meth:`OverlappedDataParallel.value_and_sync`:
    segmented backward with each bucket's reduce-scatter AND its sharded
    optimizer update (the cross-replica weight-update sharding of arXiv
    2004.13336) emitted as soon as the segment's gradients are ready.

    ``opt`` is a ``DistributedFusedAdam``/``DistributedFusedLAMB``
    constructed with ``overlap=True``; ``state`` comes from
    ``opt.init(segment_params)`` (the bucket plan is derived from the
    same segment boundaries, so bucket *i*'s shard update is
    data-dependent only on bucket *i*'s scattered grads). LAMB with
    ``max_grad_norm > 0`` needs the GLOBAL grad norm before any update
    — its scatters still interleave with the backward, but the (cheap,
    scalar-joined) updates run after the walk; see
    docs/parallelism.md's composition matrix.

    Returns ``(loss, new_segment_params, new_state)`` (plus the stats
    dict last when ``opt.numerics`` is set).
    """
    if not getattr(opt, "overlap", False):
        raise ValueError("overlapped_zero_step needs an optimizer "
                         "constructed with overlap=True")
    K = len(segments)
    if K != len(segment_params):
        raise ValueError(
            f"{K} segment fns vs {len(segment_params)} param groups")
    plan = opt.overlap_plan(segment_params)
    reg = get_registry()
    if reg.enabled:
        reg.event("overlap", "plan", segments=K,
                  buckets=[len(s) for s in plan], zero=True,
                  compress=opt.grad_compress or "none")

    carry = x
    vjps = []
    for k in range(K):
        carry, vjp = jax.vjp(segments[k], segment_params[k], carry)
        vjps.append(vjp)
    loss = carry
    if jnp.shape(loss) != ():
        raise ValueError(
            f"the last segment must return a scalar loss, got shape "
            f"{jnp.shape(loss)}")

    noop = (jnp.zeros((), jnp.float32) if found_inf is None
            else jnp.asarray(found_inf, jnp.float32))
    step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
    two_phase = opt.overlap_needs_global_norm
    deferred = []          # (k, bi, n, g_shard, new_residual)
    new_params = [None] * K
    new_buckets = [list(seg) for seg in state["buckets"]]
    stats = {} if opt.numerics else None

    ct = jnp.ones_like(loss)
    seq = 0
    bucket_no = sum(len(s) for s in plan)
    leaves_by_seg = [None] * K
    treedefs = [None] * K
    for k in reversed(range(K)):
        with _telemetry_trace.span(f"ddp_overlap_segment_{k}",
                                   role="segment", segment=k, seq=seq):
            gk, ct = vjps[k](ct)
        seq += 1
        if stats is not None:
            depth = (_numerics.default_prefix_depth()
                     if opt.numerics is True else int(opt.numerics))
            stats.update(_numerics.tree_stats(gk, prefix_depth=depth,
                                              prefix="grads"))
        g_leaves, treedef = jax.tree_util.tree_flatten(gk)
        p_leaves = jax.tree_util.tree_leaves(segment_params[k])
        leaves_by_seg[k] = p_leaves
        treedefs[k] = treedef
        bucket_no -= len(plan[k])
        for bi, bucket in enumerate(plan[k]):
            n = bucket_no + bi
            bstate = state["buckets"][k][bi]
            with _telemetry_trace.span(f"ddp_overlap_bucket_{n}",
                                       role="bucket", segment=k,
                                       seq=seq, elements=bucket.n,
                                       zero=True):
                flat_g = jnp.concatenate(
                    [g_leaves[i].reshape(-1).astype(jnp.float32)
                     for i in bucket.leaf_idx]) / scale
                flat_g = jnp.pad(flat_g, (0, bucket.padded - bucket.n))
                g_shard, new_residual = opt.bucket_reduce(flat_g, bstate)
                if not two_phase:
                    new_leaves, nb = opt.bucket_update_gather(
                        g_shard, bstate, bucket,
                        [p_leaves[i] for i in bucket.leaf_idx],
                        lr=lr, step=step, noop=noop,
                        new_residual=new_residual)
                    for i, leaf in zip(bucket.leaf_idx, new_leaves):
                        p_leaves[i] = leaf
                    new_buckets[k][bi] = nb
                else:
                    deferred.append((k, bi, bucket, g_shard,
                                     new_residual))
            seq += 1

    if two_phase:
        clip = opt.overlap_global_clip(
            [g for (_, _, _, g, _) in deferred])
        for k, bi, bucket, g_shard, new_residual in deferred:
            p_leaves = leaves_by_seg[k]
            bstate = state["buckets"][k][bi]
            new_leaves, nb = opt.bucket_update_gather(
                g_shard, bstate, bucket,
                [p_leaves[i] for i in bucket.leaf_idx],
                lr=lr, step=step, noop=noop, clip=clip,
                new_residual=new_residual)
            for i, leaf in zip(bucket.leaf_idx, new_leaves):
                p_leaves[i] = leaf
            new_buckets[k][bi] = nb

    for k in range(K):
        new_params[k] = jax.tree_util.tree_unflatten(
            treedefs[k], leaves_by_seg[k])
    new_state = {"step": step,
                 "buckets": tuple(tuple(seg) for seg in new_buckets)}
    if stats is not None:
        return loss, new_params, new_state, stats
    return loss, new_params, new_state
