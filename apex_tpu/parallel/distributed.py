"""DistributedDataParallel over a mesh axis.

Parity: reference apex/parallel/distributed.py:131-643. The reference
registers per-param grad hooks, buckets grads into dtype-segregated flat
buffers, and overlaps NCCL allreduce with backward on side streams. Options
re-expressed here: ``allreduce_always_fp32`` (150), ``gradient_average``
(152), ``gradient_predivide_factor`` (153), ``message_size`` bucketing
(accepted; XLA fuses/schedules collectives itself).

TPU design: gradients are a pytree produced by ``jax.grad`` inside a jitted
step; ``all_reduce_gradients`` runs ``lax.psum``/``pmean`` over the 'dp'
mesh axis. XLA's latency-hiding scheduler overlaps these collectives with
remaining backward compute — the stream machinery the reference builds by
hand. ``flatten``/``unflatten`` (apex_C parity, csrc/flatten_unflatten.cpp)
are provided for bucket-style IO and the C++ runtime.
"""

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu import _C
from apex_tpu.parallel import compression
from apex_tpu.parallel.compression import init_residual  # noqa: F401
from apex_tpu.telemetry import comm as _telemetry_comm
from apex_tpu.telemetry import numerics as _numerics
from apex_tpu.telemetry import trace as _telemetry_trace


def _numerics_depth(numerics):
    """Resolve the ``numerics=`` knob: True -> env/default grouping
    depth, an int -> that depth."""
    return (_numerics.default_prefix_depth() if numerics is True
            else int(numerics))


def _grad_sync_stats(local_grads, synced_grads, numerics):
    """The two stat groups the DDP ``numerics=`` knob exposes:
    ``grads/<prefix>`` from the LOCAL PRE-COMPRESSION gradients (an
    int8 psum can launder a replica's NaN into finite wire garbage, so
    only the local view sees the true non-finite source — same
    reasoning as the guard flag) and ``synced/<prefix>`` from the
    post-collective (dequantized) gradients, so int8 quantization error
    is directly observable as the dequant-vs-source rms delta per
    module prefix."""
    depth = _numerics_depth(numerics)
    stats = _numerics.tree_stats(local_grads, prefix_depth=depth,
                                 prefix="grads")
    stats.update(_numerics.tree_stats(synced_grads, prefix_depth=depth,
                                      prefix="synced"))
    return stats


def flatten(tensors):
    """Coalesce a list of SAME-dtype arrays into one flat buffer
    (parity: apex_C.flatten, csrc/flatten_unflatten.cpp).

    Contract: all leaves share one dtype, so ``unflatten(flatten(ts),
    ts)`` is bitwise round-trip-exact. ``jnp.concatenate`` would
    otherwise silently promote a mixed-dtype list to the widest dtype
    and ``unflatten``'s cast-back would lose the excursion — the
    reference kernel only ever coalesces homogeneous buffers, and the
    bucketed allreduce path guarantees it via ``plan_buckets``'s
    dtype segregation."""
    dtypes = {jnp.dtype(t.dtype) for t in tensors}
    if len(dtypes) > 1:
        raise ValueError(
            f"flatten: mixed dtypes {sorted(d.name for d in dtypes)}; "
            f"flatten/unflatten round-trip exactly only over a single "
            f"dtype — group leaves with plan_buckets first")
    return jnp.concatenate([t.reshape(-1) for t in tensors])


def unflatten(flat, tensors):
    """Split a flat buffer back into views shaped like ``tensors``
    (parity: apex_C.unflatten). Under :func:`flatten`'s single-dtype
    contract the ``astype`` is an exact no-op; it remains to cast a
    buffer that came back from a widened comm dtype (e.g. an fp32
    allreduce of bf16 grads)."""
    outs, off = [], 0
    for t in tensors:
        n = t.size
        outs.append(flat[off:off + n].reshape(t.shape).astype(t.dtype))
        off += n
    return outs


def _axis_size_total(axis_name):
    """Axis size, with tuple axes multiplied (dp x ep replica sets);
    an empty tuple means "no reduction" (size 1)."""
    if isinstance(axis_name, (tuple, list)):
        n = 1
        for a in axis_name:
            n *= lax.axis_size(a)
        return n
    return lax.axis_size(axis_name)


def all_reduce_flag(flag, axis_name="dp"):
    """Global-OR of a scalar fault/overflow flag over the replica set —
    the one collective in the resilience guard's hot path
    (``resilience.guard.guarded_update``). One f32 lane on the wire; a
    psum is an OR because flags are non-negative. Tuple axes reduce
    over every named axis; an empty tuple (or None) is the no-op
    single-replica case, mirroring ``_psum_with_policy``."""
    if axis_name is None or (isinstance(axis_name, (tuple, list))
                             and len(axis_name) == 0):
        return jnp.asarray(flag, jnp.float32)
    flag = jnp.asarray(flag, jnp.float32)
    _telemetry_comm.record_collective(
        "psum", elements=flag.size, dtype=flag.dtype, axis_name=axis_name)
    return lax.psum(flag, axis_name)


def _psum_with_policy(g, axis_name, allreduce_always_fp32, gradient_average,
                      gradient_predivide_factor, compress=None,
                      compress_block_size=compression.BLOCK_SIZE,
                      residual=None):
    """The DDP reduction policy (reference distributed.py:429-479
    ``allreduce_bucket``): optional fp32 comm dtype, predivide before /
    postdivide after the psum, cast back to the original dtype.
    ``axis_name`` may be a tuple of mesh axes (e.g.
    ``parallel_state.get_data_parallel_axes()`` = ('dp', 'ep') when expert
    parallelism borrows devices from the replica axis); an empty tuple
    skips the reduction (used as ``expert_axis_name=()`` to leave expert
    shards untouched in a pre-sync pass, e.g. before a ZeRO optimizer
    that reduce-scatters over dp itself).

    ``compress`` selects the comm payload: None (full width, honoring
    ``allreduce_always_fp32``), "bf16" (cast payload), or "int8"
    (block-quantized with error feedback — see parallel/compression.py).
    A compress mode owns the comm dtype, so it overrides
    ``allreduce_always_fp32``. With ``compress="int8"`` the return is
    ``(g, new_residual)`` and ``residual`` (fp32, same shape as ``g``,
    zeros on step 0) is added into the payload before quantization; the
    residual lives in the pre-psum, predivided gradient domain, so keep
    ``gradient_predivide_factor`` fixed across steps. ``compress="int4"``
    (dual-quantized half-byte payload) behaves exactly like int8 — same
    residual contract at half the wire width."""
    stateful = compression.needs_residual(compress)
    if isinstance(axis_name, (tuple, list)) and len(axis_name) == 0:
        return (g, residual) if stateful else g
    orig_dtype = g.dtype
    if compress is None and allreduce_always_fp32:
        g = g.astype(jnp.float32)
    if gradient_predivide_factor != 1.0:
        g = g / gradient_predivide_factor
    if compress is not None:
        shape = g.shape
        flat_r = None if residual is None else residual.reshape(-1)
        g, new_residual = compression.psum_compressed(
            g.reshape(-1), axis_name, mode=compress, residual=flat_r,
            block_size=compress_block_size)
        g = g.reshape(shape)
    else:
        _telemetry_comm.record_collective(
            "psum", elements=g.size, dtype=g.dtype, axis_name=axis_name)
        g = lax.psum(g, axis_name)
    if gradient_average:
        n = _axis_size_total(axis_name)
        g = g / (n / gradient_predivide_factor)
    g = g.astype(orig_dtype)
    return (g, new_residual.reshape(g.shape)) if stateful else g


def _leaf_path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def all_reduce_gradients(grads, axis_name="dp", *, allreduce_always_fp32=False,
                         gradient_average=True, gradient_predivide_factor=1.0,
                         expert_param_predicate=None, expert_axis_name="dp",
                         compress=None,
                         compress_block_size=compression.BLOCK_SIZE,
                         residual=None, numerics=None):
    """Allreduce a grad pytree over a mesh axis (the DDP hot path).

    With expert parallelism (mesh has an 'ep' axis), dense params replicate
    over dp x ep while expert shards replicate over dp alone: pass
    ``axis_name=parallel_state.get_data_parallel_axes()`` plus
    ``expert_param_predicate=transformer.moe.is_expert_param`` (matched
    against the '/'-joined leaf path) so each group reduces over the right
    replica set. Reducing an MoE model over 'dp' alone silently diverges
    the dense params across ep.

    ``compress=None|"bf16"|"int8"|"int4"`` selects the comm payload (see
    parallel/compression.py). With ``"int8"``/``"int4"`` the return
    becomes ``(grads, residual)`` — carry the residual pytree to the
    next call (``residual=None`` starts from zeros).

    ``numerics=True`` (or an int grouping depth) appends a per-module
    stats dict as the LAST return element — ``grads/<prefix>`` rows
    from the local pre-compression gradients, ``synced/<prefix>`` from
    the post-collective result (telemetry/numerics.py; in-graph, no
    host callback). Feed it to a
    :class:`~apex_tpu.telemetry.recorder.FlightRecorder` /
    ``resilience.guarded_update(stats=...)``.
    """
    if numerics:
        out = all_reduce_gradients(
            grads, axis_name,
            allreduce_always_fp32=allreduce_always_fp32,
            gradient_average=gradient_average,
            gradient_predivide_factor=gradient_predivide_factor,
            expert_param_predicate=expert_param_predicate,
            expert_axis_name=expert_axis_name, compress=compress,
            compress_block_size=compress_block_size, residual=residual)
        if compression.needs_residual(compress):
            synced, new_residual = out
            return synced, new_residual, _grad_sync_stats(grads, synced,
                                                          numerics)
        return out, _grad_sync_stats(grads, out, numerics)

    if compression.needs_residual(compress):
        if residual is None:
            residual = init_residual(grads)
        paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(grads)
        res_leaves = jax.tree_util.tree_leaves(residual)
        new_g, new_r = [], []
        for (path, g), r in zip(paths_leaves, res_leaves):
            ax = axis_name
            if expert_param_predicate is not None and \
                    expert_param_predicate(_leaf_path_str(path)):
                ax = expert_axis_name
            g2, r2 = _psum_with_policy(
                g, ax, allreduce_always_fp32, gradient_average,
                gradient_predivide_factor, compress=compress,
                compress_block_size=compress_block_size, residual=r)
            new_g.append(g2)
            new_r.append(r2)
        return (jax.tree_util.tree_unflatten(treedef, new_g),
                jax.tree_util.tree_unflatten(treedef, new_r))

    if expert_param_predicate is None:
        return jax.tree_util.tree_map(
            lambda g: _psum_with_policy(g, axis_name, allreduce_always_fp32,
                                        gradient_average,
                                        gradient_predivide_factor,
                                        compress=compress,
                                        compress_block_size=compress_block_size),
            grads)

    def fix(path, g):
        ax = (expert_axis_name if expert_param_predicate(_leaf_path_str(path))
              else axis_name)
        return _psum_with_policy(g, ax, allreduce_always_fp32,
                                 gradient_average, gradient_predivide_factor,
                                 compress=compress,
                                 compress_block_size=compress_block_size)

    return jax.tree_util.tree_map_with_path(fix, grads)


def plan_buckets(leaves, message_size=10000000):
    """Host-side bucket planning (reference distributed.py:287-320
    ``sync_bucket_structure``): group the flat leaf list into
    dtype-segregated, in-order buckets capped at ``message_size`` elements.
    Planning runs in the native runtime (apex_tpu_C.assign_buckets).

    Returns a list of buckets, each a list of leaf indices.
    """
    by_dtype = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype).name, []).append(i)
    buckets = []
    for idxs in by_dtype.values():
        sizes = [int(leaves[i].size) for i in idxs]
        ids = _C.assign_buckets(sizes, message_size)
        cur, cur_id = [], 0
        for i, b in zip(idxs, ids):
            if b != cur_id:
                buckets.append(cur)
                cur, cur_id = [], b
            cur.append(i)
        if cur:
            buckets.append(cur)
    return buckets


def all_reduce_gradients_bucketed(grads, axis_name="dp", *,
                                  message_size=10000000,
                                  allreduce_always_fp32=False,
                                  gradient_average=True,
                                  gradient_predivide_factor=1.0,
                                  expert_param_predicate=None,
                                  expert_axis_name="dp",
                                  compress=None,
                                  compress_block_size=compression.BLOCK_SIZE,
                                  residual=None):
    """Bucketed DDP allreduce: flatten same-dtype runs of leaves into
    ``message_size``-element buckets and psum each bucket as ONE collective
    (reference allreduce_bucket over apex_C-flattened buffers,
    distributed.py:429-479). Fewer, larger ICI collectives than the
    per-leaf path; use inside a jitted step. Expert-parallel handling as in
    :func:`all_reduce_gradients` — expert leaves bucket separately and
    reduce over ``expert_axis_name``.

    ``compress`` works per BUCKET (one quantization grid per flat
    buffer — fewer ragged tails than per-leaf); with ``"int8"`` or
    ``"int4"`` the return is ``(grads, residual)`` and the residual
    pytree stays leaf-shaped (it is flattened into the bucket alongside
    the grads), so the same residual state works for either sync
    path."""
    stateful = compression.needs_residual(compress)
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(grads)
    leaves = [l for _, l in paths_leaves]
    if stateful:
        if residual is None:
            residual = init_residual(grads)
        res_leaves = jax.tree_util.tree_leaves(residual)
    if expert_param_predicate is None:
        groups = [(axis_name, list(range(len(leaves))))]
    else:
        expert = [i for i, (p, _) in enumerate(paths_leaves)
                  if expert_param_predicate(_leaf_path_str(p))]
        expert_set = set(expert)
        dense = [i for i in range(len(leaves)) if i not in expert_set]
        groups = [(axis_name, dense), (expert_axis_name, expert)]
    out = [None] * len(leaves)
    out_res = [None] * len(leaves)
    n = 0
    for ax, idxs in groups:
        if not idxs:
            continue
        for bucket in plan_buckets([leaves[i] for i in idxs], message_size):
            bucket = [idxs[j] for j in bucket]
            # named_scope = the TPU analog of the reference's NVTX ranges
            # around allreduce_bucket (distributed.py:429, prof flag)
            with jax.named_scope(f"ddp_allreduce_bucket_{n}"):
                flat = flatten([leaves[i] for i in bucket])
                if stateful:
                    flat_r = flatten([res_leaves[i] for i in bucket])
                    flat, flat_r = _psum_with_policy(
                        flat, ax, allreduce_always_fp32, gradient_average,
                        gradient_predivide_factor, compress=compress,
                        compress_block_size=compress_block_size,
                        residual=flat_r)
                    for i, piece in zip(
                            bucket,
                            unflatten(flat_r,
                                      [res_leaves[i] for i in bucket])):
                        out_res[i] = piece
                else:
                    flat = _psum_with_policy(flat, ax, allreduce_always_fp32,
                                             gradient_average,
                                             gradient_predivide_factor,
                                             compress=compress,
                                             compress_block_size=
                                             compress_block_size)
                for i, piece in zip(
                        bucket, unflatten(flat, [leaves[i] for i in bucket])):
                    out[i] = piece
            n += 1
    if stateful:
        return (jax.tree_util.tree_unflatten(treedef, out),
                jax.tree_util.tree_unflatten(treedef, out_res))
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_params(params, axis_name="dp"):
    """Make params bitwise-identical across the axis (or tuple of axes) by
    broadcasting rank 0 (parity: DDP ctor broadcast, reference
    distributed.py:257)."""
    axes = (axis_name,) if not isinstance(axis_name, (tuple, list)) \
        else tuple(axis_name)

    def bcast(p):
        rank = jnp.zeros((), jnp.int32)
        for a in axes:
            rank = rank * lax.axis_size(a) + lax.axis_index(a)
        masked = jnp.where(rank == 0, p, jnp.zeros_like(p))
        return lax.psum(masked, axes)

    return jax.tree_util.tree_map(bcast, params)


class DistributedDataParallel:
    """Wrap a loss/grad computation with dp-axis gradient sync.

    Two usage modes:

    1. Wrap a grad function to sync its output (hook-parity)::

         ddp = DistributedDataParallel(axis_name="dp")
         grads = ddp.sync(grads)          # inside shard_map/pmap

    2. Wrap an apply fn so ``jax.grad`` of the wrapped fn yields synced
       grads automatically (closest to the reference's module wrapper —
       gradients of all params are averaged during backward)::

         model_fn = ddp(model_fn)         # psum-of-grads via custom_vjp
    """

    def __init__(self, module: Optional[Callable] = None, message_size: int = 10000000,
                 delay_allreduce: bool = False, shared_param: Any = None,
                 allreduce_trigger_params: Any = None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators: Any = None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 gradient_average_split_factor: Any = None,
                 prof: bool = False,
                 axis_name: str = "dp",
                 expert_param_predicate: Optional[Callable] = None,
                 expert_axis_name: str = "dp",
                 compress: Optional[str] = None,
                 compress_block_size: int = compression.BLOCK_SIZE,
                 numerics=None):
        self.module = module
        self.axis_name = axis_name
        self.message_size = message_size
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.delay_allreduce = delay_allreduce
        self.needs_refresh = True
        # Expert parallelism: dense params sync over axis_name (pass
        # parallel_state.get_data_parallel_axes() = ('dp','ep')), expert
        # shards over expert_axis_name. Supported in .sync(); the
        # module-wrapping mode syncs every param uniformly.
        self.expert_param_predicate = expert_param_predicate
        self.expert_axis_name = expert_axis_name
        # Compressed gradient collectives (parallel/compression.py):
        # None | "bf16" | "int8" | "int4". The int modes make .sync
        # stateful — it returns (grads, residual) and the caller
        # threads the residual pytree through the jitted step (donate
        # it like optimizer state).
        self.compress = compress
        self.compress_block_size = compress_block_size
        # In-graph numerics (telemetry/numerics.py): True / an int
        # grouping depth makes .sync also return a per-module stats
        # dict — pre-compression local grads + post-sync (dequantized)
        # grads, so int8 quantization error shows as a rms delta.
        self.numerics = numerics

    def init_residual(self, grads_or_params):
        """Zero error-feedback state for ``compress="int8"``/``"int4"``
        (a pytree shaped like the grads; donate it through the train
        step)."""
        return init_residual(grads_or_params)

    def memory_report(self, jitted_step, *args, **kwargs):
        """HBM accounting for the jitted step this DDP instance syncs
        inside (``telemetry.memory.step_memory`` — XLA's own
        ``memory_analysis()`` -> argument/output/temp bytes, peak, and
        the ``memory/hbm_headroom`` gauge), tagged with the sync
        config: the int8 payload trades wire bytes for quantization
        temps, and this is where that trade shows up as bytes. Host-
        side AOT only — never dispatches the step. Returns the report
        dict (None when the backend offers no analysis)."""
        from apex_tpu.telemetry import memory as _memory

        report = _memory.step_memory(jitted_step, *args, **kwargs)
        if report is not None:
            report = dict(report, compress=self.compress or "none",
                          axis_name=str(self.axis_name))
        return report

    def sync(self, grads, residual=None):
        """Bucketed grad allreduce honoring ``message_size`` (reference
        create_hooks bucketing); pass ``message_size=None`` at construction
        for the per-leaf path.

        With ``compress="int8"`` or ``"int4"`` returns
        ``(grads, residual)``; pass the previous step's residual in
        (``None`` starts from zeros — step 0 of error feedback). With ``numerics=`` set at construction, a
        per-module stats dict (``grads/*`` pre-compression local,
        ``synced/*`` post-collective — see ``_grad_sync_stats``) is
        appended as the last return element, for either sync path."""
        kw = {}
        if self.compress is not None:
            kw = dict(compress=self.compress,
                      compress_block_size=self.compress_block_size)
            if compression.needs_residual(self.compress):
                kw["residual"] = residual
        # host-side span (trace-time when called inside jit); the comm
        # byte counters accumulate underneath via _psum_with_policy
        with _telemetry_trace.span("ddp/sync",
                                   compress=self.compress or "none",
                                   bucketed=bool(self.message_size),
                                   numerics=bool(self.numerics)):
            if self.message_size:
                out = all_reduce_gradients_bucketed(
                    grads, self.axis_name, message_size=self.message_size,
                    allreduce_always_fp32=self.allreduce_always_fp32,
                    gradient_average=self.gradient_average,
                    gradient_predivide_factor=self.gradient_predivide_factor,
                    expert_param_predicate=self.expert_param_predicate,
                    expert_axis_name=self.expert_axis_name, **kw)
            else:
                out = all_reduce_gradients(
                    grads, self.axis_name,
                    allreduce_always_fp32=self.allreduce_always_fp32,
                    gradient_average=self.gradient_average,
                    gradient_predivide_factor=self.gradient_predivide_factor,
                    expert_param_predicate=self.expert_param_predicate,
                    expert_axis_name=self.expert_axis_name, **kw)
            if not self.numerics:
                return out
            if compression.needs_residual(self.compress):
                synced, new_residual = out
                return synced, new_residual, _grad_sync_stats(
                    grads, synced, self.numerics)
            return out, _grad_sync_stats(grads, out, self.numerics)

    def __call__(self, fn=None, *args, **kwargs):
        """If constructed around a module/apply fn, call it; DDP on TPU is
        transparent in forward (sync happens on gradients).

        Gradient-sync note: under JAX's shard_map, cotangents of
        *replicated* params are summed across the axis automatically at the
        shard_map boundary (the vma-typed transpose) — the allreduce the
        reference implements with hooks+NCCL. The wrapper therefore only
        applies the averaging / predivide policy by scaling the backward
        cotangent; ``sync``/``all_reduce_gradients`` remain for grads of
        per-device (varying) params.
        """
        target = fn if callable(fn) and self.module is None else self.module
        if target is None:
            raise TypeError("DistributedDataParallel needs a callable module")
        if self.expert_param_predicate is not None:
            raise NotImplementedError(
                "expert_param_predicate requires per-param axis selection; "
                "use DistributedDataParallel(...).sync(grads) instead of "
                "the module-wrapping mode")
        if fn is not None and target is self.module:
            args = (fn,) + args

        axis_name = self.axis_name
        gradient_average = self.gradient_average

        @functools.wraps(target)
        def wrapped(*a, **kw):
            inner = functools.partial(target, **kw) if kw else target
            return _ddp_identity(inner, axis_name, gradient_average, *a)

        if callable(fn) and self.module is None:
            return wrapped
        return wrapped(*args, **kwargs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ddp_identity(fn, axis_name, gradient_average, *args):
    return fn(*args)


def _ddp_fwd(fn, axis_name, gradient_average, *args):
    out, vjp = jax.vjp(fn, *args)
    return out, vjp


def _ddp_bwd(fn, axis_name, gradient_average, vjp, g):
    # Two shard_map autodiff regimes exist (JAX >= 0.8):
    # - checked (vma typing on): cotangents of replicated params are
    #   auto-psummed at the shard_map boundary, so DDP only applies the
    #   averaging policy by scaling the cotangent.
    # - unchecked (check_vma=False): cotangents stay per-device, so DDP
    #   performs the allreduce itself.
    # Discriminate via the vma type of axis_index (varying iff checking
    # on). shard_map sets check_vma uniformly, but probe every axis of a
    # tuple axis_name and insist they agree rather than trusting the
    # first one.
    axes = (tuple(axis_name) if isinstance(axis_name, (tuple, list))
            else (axis_name,))
    if not hasattr(jax, "typeof"):
        # jax < 0.6 has no vma typing at all: the experimental shard_map
        # used there runs check_rep=False (apex_tpu.testing.shard_map),
        # i.e. always the unchecked regime — DDP performs the allreduce.
        states = {False}
    else:
        states = {
            ax in getattr(jax.typeof(lax.axis_index(ax)), "vma", frozenset())
            for ax in axes}
    if len(states) != 1:
        raise ValueError(
            f"mixed vma checking states across mesh axes {axes}; DDP "
            f"cannot tell whether the shard_map boundary will psum "
            f"cotangents")
    checked = states.pop()
    if checked:
        if gradient_average:
            n = _axis_size_total(axis_name)
            g = jax.tree_util.tree_map(lambda c: c / n, g)
        return vjp(g)
    grads = vjp(g)
    return tuple(
        all_reduce_gradients(gr, axis_name, gradient_average=gradient_average)
        for gr in grads)


_ddp_identity.defvjp(_ddp_fwd, _ddp_bwd)


class Reducer:
    """Manual-trigger gradient reducer (parity: reference
    distributed.py:91-128 — user calls ``.reduce()`` when ready)."""

    def __init__(self, module_or_grads_list=None, axis_name="dp"):
        self.axis_name = axis_name

    def reduce(self, grads, **kwargs):
        return all_reduce_gradients(grads, self.axis_name, **kwargs)
