"""Multi-host process launcher.

Parity: reference apex/parallel/multiproc.py (35 LoC): a pre-torchrun
helper that spawns one training process per GPU with RANK/WORLD_SIZE env.

TPU design: a single process drives all local chips (SPMD), so per-chip
spawning is unnecessary; the launcher's job is *multi-host* bring-up:
set the jax.distributed coordinates and exec the training script once per
host. Usage (one invocation per host, e.g. from your scheduler):

    python -m apex_tpu.parallel.multiproc --nnodes 4 --node_rank $I \
        --coordinator host0:1234 train.py --arg ...
"""

import os
import subprocess
import sys


def initialize_distributed(coordinator=None, num_processes=None,
                           process_id=None):
    """Initialize jax.distributed from args or the env this launcher sets
    (the analog of torch.distributed.init_process_group)."""
    import jax

    if coordinator is None:
        coordinator = os.environ.get("APEX_TPU_COORDINATOR")
    if num_processes is None:
        num_processes = os.environ.get("APEX_TPU_NUM_PROCESSES")
    if process_id is None:  # explicit 0 (host 0) must win over the env
        process_id = os.environ.get("APEX_TPU_PROCESS_ID")
    if coordinator is None:
        return  # single host
    if num_processes is None or process_id is None:
        raise ValueError(
            "initialize_distributed: num_processes and process_id are "
            "required when a coordinator is set (pass them or export "
            "APEX_TPU_NUM_PROCESSES / APEX_TPU_PROCESS_ID)")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes),
        process_id=int(process_id))


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    nnodes, node_rank, coordinator = 1, 0, None
    while argv and argv[0].startswith("--"):
        flag = argv.pop(0)
        if flag in ("--nnodes", "--node_rank", "--coordinator") and not argv:
            raise SystemExit(f"{flag} requires a value")
        if flag == "--nnodes":
            nnodes = int(argv.pop(0))
        elif flag == "--node_rank":
            node_rank = int(argv.pop(0))
        elif flag == "--coordinator":
            coordinator = argv.pop(0)
        else:
            raise SystemExit(f"unknown flag {flag}")
    if not argv:
        raise SystemExit(
            "usage: multiproc [--nnodes N --node_rank I --coordinator "
            "host:port] script.py [args...]")
    env = dict(os.environ)
    if coordinator is not None:
        env["APEX_TPU_COORDINATOR"] = coordinator
        env["APEX_TPU_NUM_PROCESSES"] = str(nnodes)
        env["APEX_TPU_PROCESS_ID"] = str(node_rank)
    cmd = [sys.executable] + argv
    raise SystemExit(subprocess.call(cmd, env=env))


if __name__ == "__main__":
    main()
