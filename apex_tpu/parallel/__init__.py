"""apex_tpu.parallel — data-parallel runtime.

Parity: reference apex/parallel/__init__.py exports DistributedDataParallel,
Reducer, SyncBatchNorm, convert_syncbn_model, create_syncbn_process_group,
LARC.

TPU design: data parallelism is a mesh axis, not a process group. DDP's
autograd-hook/bucket/stream machinery (reference apex/parallel/
distributed.py:323-479) collapses into a gradient ``psum`` inside one
jitted train step; XLA's latency-hiding scheduler overlaps the allreduce
with the backward pass — the same overlap the reference hand-builds with
CUDA streams.
"""

from apex_tpu.parallel import compression  # noqa: F401
from apex_tpu.parallel.compression import init_residual  # noqa: F401
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    Reducer,
    all_reduce_gradients,
    all_reduce_gradients_bucketed,
    broadcast_params,
    flatten,
    plan_buckets,
    unflatten,
)
from apex_tpu.parallel import mesh2d  # noqa: F401
from apex_tpu.parallel import multiproc  # noqa: F401
from apex_tpu.parallel import overlap  # noqa: F401
from apex_tpu.parallel import pipeline  # noqa: F401
from apex_tpu.parallel.overlap import (  # noqa: F401
    OverlappedDataParallel,
    overlapped_zero_step,
    plan_overlap,
)
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, sync_batch_norm  # noqa: F401
from apex_tpu.parallel.LARC import LARC  # noqa: F401


def convert_syncbn_model(module, process_group=None, channel_last=False):
    """Swap BatchNorm layers for SyncBatchNorm in an apex_tpu model.

    Parity: reference apex/parallel/__init__.py:21-97. Works on apex_tpu
    model classes that expose a ``norm_cls``/``bn_axis_name`` knob (flax
    modules are frozen dataclasses, so conversion is a ``replace``).
    """
    import dataclasses

    import flax.linen as nn

    if hasattr(module, "norm_cls"):
        return dataclasses.replace(module, norm_cls=SyncBatchNorm)
    if hasattr(module, "bn_axis_name"):
        return dataclasses.replace(module, bn_axis_name=process_group or "dp")
    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            use_running_average=module.use_running_average,
            momentum=module.momentum, epsilon=module.epsilon,
            axis_name=process_group or "dp")
    raise TypeError(
        "convert_syncbn_model: pass an apex_tpu model exposing `norm_cls` or "
        "`bn_axis_name`, or build with apex_tpu.parallel.SyncBatchNorm directly.")


def create_syncbn_process_group(group_size):
    """Return the mesh-axis spec for group-limited sync-BN.

    Parity: reference apex/parallel/__init__.py create_syncbn_process_group
    (sync within subgroups of ``group_size`` ranks). On a mesh this is a
    reshaped dp axis: callers split 'dp' into ('dp_outer', 'dp_bn') and
    sync-BN over 'dp_bn' only.
    """
    if group_size == 0:
        return None
    return ("dp_bn", group_size)
