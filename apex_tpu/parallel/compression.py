"""Block-quantized gradient collectives with error feedback.

Why: every collective in the DP runtime moves gradients at full width —
``_psum_with_policy`` even *upcasts* to fp32 under ``allreduce_always_fp32``
— and the ZeRO optimizers ship full-precision shards both ways through
their ``psum_scatter``/``all_gather`` round trip. EQuARX (arxiv
2506.17615) shows a block-scaled quantized AllReduce inside XLA cuts DP
grad-sync bytes ~4x with negligible accuracy loss; this module is that
comm story for the apex_tpu collectives.

Scheme (``mode="int8"``): the flat bucket is padded to whole
``block_size``-element blocks (ragged tail zero-padded); per-block absmax
scales are computed locally and the per-replica scales are combined with
``lax.pmax`` — the all-gather-the-scales-and-take-max exchange fused into
one tiny collective — so every replica quantizes against the SAME scale
grid; values are rounded to int8 in [-127, 127]; the payload is summed as
**int32 partials** (a psum of <= 2^24 int8 lanes is exact in int32, and a
production quantized allreduce — EQuARX's — ships the int8 payload on the
wire; :func:`estimate_allreduce_bytes` models those wire bytes); the sum
is dequantized with the shared scales. The local quantization error
``g_eff - q*s`` is returned as the **error-feedback residual**: callers
must add it back into the next step's gradient (EF-SGD), which is what
keeps int8 training within noise of the fp32 baseline. The residual is an
explicit pytree/array so it composes with jit and buffer donation.

``mode="bf16"`` is a passthrough-cast mode: the payload is bf16 on the
wire (2x fewer bytes, no residual needed — and exact when the gradients
are already bf16).

``mode="int4"`` pushes the same machinery to 4 bits with EQuARX-style
DUAL quantization (apex_tpu.kernels.quant4): symmetric int4 values in
[-7, 7] against per-block scales that are THEMSELVES uint8-quantized
relative to one fp32 per-bucket scale, so the modeled wire is ~0.53
bytes/element at block 256 (values 0.5 + scales 1/256 + one fp32). The
error-feedback residual machinery is shared verbatim with int8 — only
the per-step quantization error is larger (EF absorbs it; the 200-step
convergence test holds the same 2% bound).

The quantize/dequantize kernels ride the kernel registry
(:mod:`apex_tpu.kernels.registry`): gates ``quant`` (int8) and
``quant4``, master switch ``APEX_TPU_KERNELS``, the legacy
``APEX_TPU_COMPRESS_PALLAS`` still honored with a DeprecationWarning;
:func:`force_interpret` runs them in interpreter mode for CPU tests.
Off TPU the pure-``jnp`` formulations below are both the fallback and
the kernels' parity oracles.
"""

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.kernels import quant4 as _quant4
from apex_tpu.kernels.registry import kernel_gate
from apex_tpu.telemetry import comm as _telemetry_comm

# ~256 lanes per scale: 2 TPU lane-groups wide, 0.4% scale overhead.
BLOCK_SIZE = 256

# int8 symmetric range; -128 is excluded so the grid is symmetric and
# dequantization is a pure scale multiply.
_QMAX = 127.0

# compression modes whose collectives return an error-feedback residual
RESIDUAL_MODES = ("int8", "int4")

_GATE = kernel_gate("quant", legacy_env="APEX_TPU_COMPRESS_PALLAS")


def needs_residual(mode) -> bool:
    """Whether ``mode`` makes the compressed collectives stateful —
    returning ``(result, new_residual)`` for error feedback."""
    return mode in RESIDUAL_MODES


def _gate():
    return _GATE


def force_interpret(on: bool):
    """Run the Pallas quantize/dequantize kernels (int8 AND int4) in
    interpreter mode regardless of backend (tests: exercises the kernel
    dataflow on the CPU mesh)."""
    _GATE.force_interpret(on)
    _quant4.GATE.force_interpret(on)


def num_blocks(n: int, block_size: int = BLOCK_SIZE) -> int:
    return -(-n // block_size)


def pad_to_blocks(flat, block_size: int = BLOCK_SIZE):
    """[n] -> [nblocks, block_size] fp32, ragged tail zero-padded."""
    n = flat.shape[0]
    nb = num_blocks(n, block_size)
    flat = jnp.pad(flat.astype(jnp.float32), (0, nb * block_size - n))
    return flat.reshape(nb, block_size)


def block_scales(x2d):
    """Per-block symmetric scale: absmax/127, floored so an all-zero
    block dequantizes to zeros instead of NaN."""
    absmax = jnp.max(jnp.abs(x2d), axis=-1, keepdims=True)
    return jnp.maximum(absmax, 1e-12) / _QMAX


# ---------------------------------------------------------------------------
# quantize / dequantize: pure-jnp formulation + Pallas kernel
# ---------------------------------------------------------------------------

def _quantize_jnp(x2d, scales):
    return jnp.clip(jnp.round(x2d / scales), -_QMAX, _QMAX).astype(jnp.int8)


def _dequantize_jnp(q2d, scales):
    return q2d.astype(jnp.float32) * scales


# fp32 rows tile at 8 sublanes, int8 output rows at 32 — one grid cell
# handles 32 blocks so both operand tilings are legal.
_ROWS_PER_CELL = 32


def _quant_kernel(x_ref, s_ref, q_ref):
    q_ref[...] = jnp.clip(jnp.round(x_ref[...] / s_ref[...]),
                          -_QMAX, _QMAX).astype(jnp.int8)


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[...]


def _pad_rows(x2d, rows):
    nb = x2d.shape[0]
    pad = (-nb) % rows
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d, nb


def _quantize_pallas(x2d, scales):
    from jax.experimental import pallas as pl

    bs = x2d.shape[1]
    x2d, nb = _pad_rows(x2d, _ROWS_PER_CELL)
    # pad scales with ones: the padded rows divide by 1, not by 0
    s = jnp.concatenate(
        [scales, jnp.ones((x2d.shape[0] - nb, 1), jnp.float32)])
    q = pl.pallas_call(
        _quant_kernel,
        grid=(x2d.shape[0] // _ROWS_PER_CELL,),
        in_specs=[pl.BlockSpec((_ROWS_PER_CELL, bs), lambda i: (i, 0)),
                  pl.BlockSpec((_ROWS_PER_CELL, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_ROWS_PER_CELL, bs), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
        interpret=_gate().interpret,
    )(x2d, s)
    return q[:nb]


def _dequantize_pallas(q2d, scales):
    from jax.experimental import pallas as pl

    bs = q2d.shape[1]
    q2d, nb = _pad_rows(q2d, _ROWS_PER_CELL)
    s, _ = _pad_rows(scales, _ROWS_PER_CELL)
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(q2d.shape[0] // _ROWS_PER_CELL,),
        in_specs=[pl.BlockSpec((_ROWS_PER_CELL, bs), lambda i: (i, 0)),
                  pl.BlockSpec((_ROWS_PER_CELL, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_ROWS_PER_CELL, bs), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(q2d.shape, jnp.float32),
        interpret=_gate().interpret,
    )(q2d, s)
    return out[:nb]


def quantize_blockwise(flat, block_size: int = BLOCK_SIZE, scales=None):
    """[n] -> (q [nblocks, block_size] int8, scales [nblocks, 1] fp32).

    ``scales=None`` computes local per-block scales; pass shared
    (pmax-combined) scales for the collective path so every replica
    lands on the same grid."""
    x2d = pad_to_blocks(flat, block_size)
    if scales is None:
        scales = block_scales(x2d)
    if _gate().enabled():
        return _quantize_pallas(x2d, scales), scales
    return _quantize_jnp(x2d, scales), scales


def dequantize_blockwise(q2d, scales, n=None):
    """(q [nblocks, b] int8/int32, scales [nblocks, 1]) -> [n] fp32."""
    if _gate().enabled():
        out = _dequantize_pallas(q2d, scales)
    else:
        out = _dequantize_jnp(q2d, scales)
    out = out.reshape(-1)
    return out if n is None else out[:n]


def quantize_rows_blockwise(x, block_size: int = BLOCK_SIZE):
    """Per-row lane-blocked symmetric int8: ``[..., F]`` ->
    ``(q [..., nb, block] int8, scales [..., nb, 1] fp32)``.

    The KV-cache quantization primitive (apex_tpu.serving.kv_cache):
    every leading-dim row (a cache position) is quantized independently
    against its own per-256-lane-block absmax scales, so appending one
    position never re-quantizes — and never drifts — the rest of the
    cache. Same grid and kernels as the flat gradient path (the Pallas
    gate applies; the parity oracle is the pure-jnp formulation)."""
    lead, n = x.shape[:-1], x.shape[-1]
    nb = num_blocks(n, block_size)
    flat = jnp.pad(x.astype(jnp.float32).reshape(-1, n),
                   ((0, 0), (0, nb * block_size - n)))
    flat = flat.reshape(-1, block_size)
    scales = block_scales(flat)
    q = (_quantize_pallas(flat, scales) if _gate().enabled()
         else _quantize_jnp(flat, scales))
    return (q.reshape(*lead, nb, block_size),
            scales.reshape(*lead, nb, 1))


def dequantize_rows_blockwise(q, scales, n=None):
    """Inverse of :func:`quantize_rows_blockwise`:
    ``(q [..., nb, block], scales [..., nb, 1])`` -> ``[..., F]`` fp32
    (``n`` truncates the zero-padded ragged tail; default keeps
    ``nb * block`` lanes)."""
    lead = q.shape[:-2]
    block_size = q.shape[-1]
    flat = q.reshape(-1, block_size)
    s = scales.reshape(-1, 1)
    out = (_dequantize_pallas(flat, s) if _gate().enabled()
           else _dequantize_jnp(flat, s))
    out = out.reshape(*lead, q.shape[-2] * block_size)
    return out if n is None else out[..., :n]


def init_residual(grads):
    """Zero error-feedback residual pytree matching ``grads`` (fp32
    leaves — the residual accumulates sub-ulp-of-bf16 errors)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.zeros(g.shape, jnp.float32), grads)


# ---------------------------------------------------------------------------
# compressed collectives (inside shard_map / pmap regions)
# ---------------------------------------------------------------------------

def _shared_scales(x2d, axis_name):
    """Per-replica block scales combined to the replica-set max — the
    all-gather of per-replica scales collapsed into one lax.pmax (bytes:
    nblocks fp32, ~0.4% of the payload at block 256)."""
    scales = block_scales(x2d)
    _telemetry_comm.record_collective(
        "pmax", elements=scales.size, dtype=jnp.float32,
        axis_name=axis_name, mode="int8")
    return lax.pmax(scales, axis_name)


def _shared_int4_scales(x2d, axis_name):
    """The int4 scale agreement: pmax the raw fp32 block absmaxes (one
    tiny collective, same as int8), then derive the two-level
    ``(sq uint8, gmax fp32)`` pair DETERMINISTICALLY from the shared
    absmaxes — every replica lands on the identical effective grid, so
    the int32-partial psum stays exact. Returns the effective
    ``[nblocks, 1]`` fp32 scales."""
    absmax = jnp.maximum(jnp.max(jnp.abs(x2d), axis=-1, keepdims=True),
                         1e-12)
    _telemetry_comm.record_collective(
        "pmax", elements=absmax.size, dtype=jnp.float32,
        axis_name=axis_name, mode="int4")
    absmax = lax.pmax(absmax, axis_name)
    sq, gmax = _quant4.int4_block_scales(absmax)
    return _quant4.effective_scales(sq, gmax)


def _psum_int4(flat, axis_name, *, residual, block_size=BLOCK_SIZE):
    """int4 body shared by :func:`psum_compressed`: quantize on the
    shared two-level grid, sum int32 partials (semantic wire: 4-bit
    lanes — ``bits=4`` in the accounting), dequantize, return the EF
    residual in the flat domain."""
    n = flat.shape[0]
    g = flat.astype(jnp.float32)
    if residual is not None:
        g = g + residual.astype(jnp.float32)
    x2d = pad_to_blocks(g, block_size)
    scales = _shared_int4_scales(x2d, axis_name)
    _quant4.record()
    q = _quant4.quantize_int4(x2d, scales)
    _telemetry_comm.record_collective(
        "psum", elements=q.size, dtype=jnp.int8, bits=4,
        axis_name=axis_name, mode="int4", emulated=True)
    total = lax.psum(q.astype(jnp.int32), axis_name)
    out = dequantize_blockwise(total, scales, n=n)
    err = (x2d - _quant4._dequantize_jnp(q, scales)).reshape(-1)[:n]
    return out, err


def psum_compressed(flat, axis_name, *, mode="int8", residual=None,
                    block_size: int = BLOCK_SIZE):
    """AllReduce-sum of a flat buffer with a compressed payload.

    Returns ``(summed flat, new_residual)``. int8: the sum is fp32 and
    ``new_residual`` is the fp32 local quantization error to feed back
    next step (``residual=None`` starts from zeros). int4 works like
    int8 at half the wire width (dual-quantized scales; see module
    docstring). bf16: payload is a bf16 cast, result is cast back to
    ``flat.dtype``, residual is passed through unchanged (None stays
    None).
    """
    if mode == "bf16":
        _telemetry_comm.record_collective(
            "psum", elements=flat.size, dtype=jnp.bfloat16,
            axis_name=axis_name, mode="bf16")
        out = lax.psum(flat.astype(jnp.bfloat16), axis_name)
        return out.astype(flat.dtype), residual
    if mode == "int4":
        return _psum_int4(flat, axis_name, residual=residual,
                          block_size=block_size)
    if mode != "int8":
        raise ValueError(f"unknown compression mode {mode!r}")
    n = flat.shape[0]
    g = flat.astype(jnp.float32)
    if residual is not None:
        g = g + residual.astype(jnp.float32)
    x2d = pad_to_blocks(g, block_size)
    scales = _shared_scales(x2d, axis_name)
    q, _ = quantize_blockwise(g, block_size, scales=scales)
    # semantic wire width: int8 lanes + the fp32 scale pmax (the psum
    # emulation ships int32 partials until XLA grows a quantized
    # collective — estimate_allreduce_bytes models the same wire format)
    _telemetry_comm.record_collective(
        "psum", elements=q.size, dtype=jnp.int8, axis_name=axis_name,
        mode="int8", emulated=True)
    total = lax.psum(q.astype(jnp.int32), axis_name)
    out = dequantize_blockwise(total, scales, n=n)
    err = (x2d - _dequantize_jnp(q, scales)).reshape(-1)[:n]
    return out, err


def psum_compressed_blocks(x2d, axis_name, *, scale_mult=None):
    """AllReduce-sum of an ALREADY block-shaped ``[nblocks, block]``
    fp32 buffer with the int8 payload — the bucket-domain primitive the
    overlapped step (parallel/overlap.py) is built on.

    The flat :func:`psum_compressed` re-marshals its error-feedback
    residual through ``flatten``/``unflatten`` every step; a step that
    keeps its residual in this 2-D block layout adds it with one
    elementwise add and skips that traffic entirely. ``x2d`` is the
    effective gradient (residual already added by the caller).

    ``scale_mult`` folds a constant post-psum multiply (e.g. the
    ``1/world`` gradient averaging) into the dequantization scales — a
    ``[nblocks, 1]`` multiply instead of a full-length pass over the
    payload. Folding changes the result by at most one fp32 rounding
    per element vs dividing afterwards; pass ``None`` for the
    bit-exact-to-:func:`psum_compressed` order of operations.

    Returns ``(summed fp32 [nblocks * block] flat, err2d)`` where
    ``err2d`` is the local quantization error in the SAME 2-D block
    layout (the next step's residual, zero pad tail included)."""
    scales = _shared_scales(x2d, axis_name)
    q = (_quantize_pallas(x2d, scales) if _gate().enabled()
         else _quantize_jnp(x2d, scales))
    _telemetry_comm.record_collective(
        "psum", elements=q.size, dtype=jnp.int8, axis_name=axis_name,
        mode="int8", emulated=True)
    total = lax.psum(q.astype(jnp.int32), axis_name)
    out_scales = scales if scale_mult is None \
        else scales * jnp.float32(scale_mult)
    out = dequantize_blockwise(total, out_scales)
    err = x2d - _dequantize_jnp(q, scales)
    return out, err


def psum_scatter_compressed(flat, axis_name, *, mode="int8", residual=None,
                            block_size: int = BLOCK_SIZE):
    """ZeRO grad sync: reduce-scatter with a compressed payload.

    ``flat`` length must be a multiple of ``world * block_size`` (int8)
    or ``world`` (bf16) — the optimizers pad to that (``_shard_info``).
    Returns ``(local summed shard fp32 [len/world], new_residual)``;
    the residual is full-length (the error lives where the *local*
    gradient was quantized, not where the shard landed).
    """
    if mode == "bf16":
        _telemetry_comm.record_collective(
            "psum_scatter", elements=flat.size, dtype=jnp.bfloat16,
            axis_name=axis_name, mode="bf16")
        shard = lax.psum_scatter(flat.astype(jnp.bfloat16), axis_name,
                                 tiled=True)
        return shard.astype(jnp.float32), residual
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown compression mode {mode!r}")
    world = lax.axis_size(axis_name)
    g = flat.astype(jnp.float32)
    if residual is not None:
        g = g + residual.astype(jnp.float32)
    x2d = pad_to_blocks(g, block_size)
    nb = x2d.shape[0]
    if mode == "int4":
        scales = _shared_int4_scales(x2d, axis_name)
        _quant4.record()
        q = _quant4.quantize_int4(x2d, scales)
        _telemetry_comm.record_collective(
            "psum_scatter", elements=q.size, dtype=jnp.int8, bits=4,
            axis_name=axis_name, mode="int4", emulated=True)
        dq = _quant4._dequantize_jnp(q, scales)
    else:
        scales = _shared_scales(x2d, axis_name)
        q = _quantize_pallas(x2d, scales) if _gate().enabled() \
            else _quantize_jnp(x2d, scales)
        _telemetry_comm.record_collective(
            "psum_scatter", elements=q.size, dtype=jnp.int8,
            axis_name=axis_name, mode="int8", emulated=True)
        dq = _dequantize_jnp(q, scales)
    total = lax.psum_scatter(q.astype(jnp.int32), axis_name, tiled=True)
    rank = lax.axis_index(axis_name)
    my_scales = lax.dynamic_slice_in_dim(scales, rank * (nb // world),
                                         nb // world)
    shard = dequantize_blockwise(total, my_scales)
    err = (x2d - dq).reshape(-1)
    return shard, err


def all_gather_compressed(shard, axis_name, *, mode="bf16",
                          block_size: int = BLOCK_SIZE):
    """ZeRO param gather: all-gather with a compressed payload.

    Unlike the emulated-int8 psum (int32 partials on the wire), a
    quantized all-gather genuinely ships int8 + scales through XLA
    today — each rank quantizes its own shard with LOCAL scales (no
    pmax needed; nothing is summed) and every receiver dequantizes the
    concatenation. Returns the full fp32 flat vector.
    """
    if mode == "bf16":
        _telemetry_comm.record_collective(
            "all_gather", elements=shard.size, dtype=jnp.bfloat16,
            axis_name=axis_name, mode="bf16")
        full = lax.all_gather(shard.astype(jnp.bfloat16), axis_name,
                              tiled=True)
        return full.astype(jnp.float32)
    if mode == "int4":
        return _all_gather_int4(shard, axis_name, block_size=block_size)
    if mode != "int8":
        raise ValueError(f"unknown compression mode {mode!r}")
    q, scales = quantize_blockwise(shard, block_size)
    _telemetry_comm.record_collective(
        "all_gather", elements=q.size, dtype=jnp.int8,
        axis_name=axis_name, mode="int8")
    _telemetry_comm.record_collective(
        "all_gather", elements=scales.size, dtype=jnp.float32,
        axis_name=axis_name, mode="int8")
    q_full = lax.all_gather(q, axis_name, tiled=True)
    s_full = lax.all_gather(scales, axis_name, tiled=True)
    return dequantize_blockwise(q_full, s_full)


def _all_gather_int4(shard, axis_name, *, block_size=BLOCK_SIZE):
    """The genuinely-int4 gather: each rank quantizes its own shard on
    LOCAL two-level scales (nothing is summed, so no pmax), PACKS the
    nibbles (apex_tpu.kernels.quant4 split-half format), and ships
    uint8 half-bytes + uint8 block scales + one fp32 per rank — real
    4-bit wire traffic through XLA today, like the int8 gather.

    When the ``fused_cc`` gate is live, quantize+pack runs as ONE
    kernel into the collective send and unpack+dequant as one kernel
    out of the receive (kernels/fused_cc family c): the int4 code
    tensor never round-trips HBM on either side of the ring.  Wire
    payloads, scales, and telemetry are identical either way."""
    from apex_tpu.kernels import fused_cc as _fused_cc

    x2d = pad_to_blocks(shard.astype(jnp.float32), block_size)
    nb = x2d.shape[0]
    absmax = jnp.maximum(jnp.max(jnp.abs(x2d), axis=-1, keepdims=True),
                         1e-12)
    sq, gmax = _quant4.int4_block_scales(absmax)
    scales = _quant4.effective_scales(sq, gmax)
    fused = _fused_cc.GATE.enabled()
    if fused:
        packed = _fused_cc.quantize_pack_int4(x2d, scales)
    else:
        _quant4.record()
        q = _quant4.quantize_int4(x2d, scales)
        packed = _quant4.pack_int4(q)
    for elems, dt in ((packed.size, jnp.uint8), (sq.size, jnp.uint8),
                      (1, jnp.float32)):
        _telemetry_comm.record_collective(
            "all_gather", elements=elems, dtype=dt,
            axis_name=axis_name, mode="int4")
    p_full = lax.all_gather(packed, axis_name, tiled=True)
    sq_full = lax.all_gather(sq, axis_name, tiled=True)
    gmax_full = lax.all_gather(gmax.reshape(1), axis_name, tiled=True)
    s_full = sq_full.astype(jnp.float32) * (
        jnp.repeat(gmax_full, nb).reshape(-1, 1)
        / jnp.float32(255.0 * _quant4.QMAX4))
    if fused:
        return _fused_cc.unpack_dequantize_int4(p_full,
                                                s_full).reshape(-1)
    q_full = _quant4.unpack_int4(p_full)
    return dequantize_blockwise(q_full, s_full)


# ---------------------------------------------------------------------------
# comm-byte accounting (bench.py)
# ---------------------------------------------------------------------------

def estimate_allreduce_bytes(n, *, world=8, compress=None,
                             block_size: int = BLOCK_SIZE,
                             dtype_bytes: int = 4):
    """Estimated bytes EACH replica transmits for one gradient
    allreduce of ``n`` elements, ring model: ``2*(w-1)/w * payload``
    (reduce-scatter + all-gather phases). int8 counts the wire format a
    production quantized allreduce ships (1 byte/elem + fp32 per-block
    scales + the scale-pmax exchange); bf16 counts 2 bytes/elem. This
    is a MODEL — the lax.psum int8 emulation moves int32 partials until
    XLA grows an EQuARX-style quantized collective — kept in one place
    so bench.py's ``comm_bytes_per_step`` stays honest about what it
    estimates."""
    if world <= 1:
        return 0
    ring = 2.0 * (world - 1) / world
    if compress is None:
        payload = n * dtype_bytes
    elif compress == "bf16":
        payload = n * 2
    elif compress == "int8":
        nb = num_blocks(n, block_size)
        payload = n * 1 + nb * 4          # int8 lanes + shared fp32 scales
        payload += nb * 4                 # the scale pmax exchange
    elif compress == "int4":
        nb = num_blocks(n, block_size)
        payload = n * 0.5 + nb * 1 + 4    # packed nibbles + uint8 block
        #                                   scales + the fp32 bucket scale
        payload += nb * 4                 # the absmax pmax exchange (fp32)
    else:
        raise ValueError(f"unknown compression mode {compress!r}")
    return int(round(ring * payload))
