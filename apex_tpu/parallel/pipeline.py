"""1F1B pipeline parallelism on a 3-D ``(data, model, pipe)`` mesh.

Why: pipeline parallelism is the last unreproduced parallelism axis
(ROADMAP item 1) — the production-pod topology is pipeline x tensor x
data, with DP bucket psums hidden inside pipeline bubbles (T3's
fine-grained compute/collective overlap, arXiv 2401.16677) and the
cross-replica weight-update sharding (arXiv 2004.13336) extended to a
three-axis shard table.

This module is two layers:

1. **The reference schedule machinery** — relocated verbatim from
   ``apex_tpu.transformer.pipeline_parallel.schedules`` /
   ``p2p_communication`` (those modules are now compat shims
   re-exporting this one): ``pipeline_schedule_plan``, the jitted
   ``lax.fori_loop`` tick machine ``_pipelined_fwd_bwd`` behind
   ``get_forward_backward_func``, and the ppermute p2p helpers. Their
   semantics and the reference parity notes are unchanged.

2. **The 3-D production substrate** — :func:`mesh_3d` /
   :func:`build_pipeline_step`: a stage-partitioned GPT-2 (mesh2d's
   column/row-parallel blocks per stage) driven by a **host-unrolled**
   1F1B schedule. Unrolling the same tick math as the fori_loop machine
   (forward unit ``k = t - rank``, backward unit
   ``kb = t - (P-1) - (P-1-rank)``, ring stash of ``min(M, 2P-1)``
   stage inputs, ``jax.vjp`` rematerialization per backward unit) buys
   what a traced loop cannot: a ``pp_tick_<t>`` telemetry span per
   tick, exactly one ``record_collective`` per *executed* stage
   transfer (so the measured ``comm/axis/pipe_*`` counters equal the
   static auditor's per-axis pricing), and the DP bucket psums traced
   into the cooldown region.

Axis-scoping rules (extends docs/parallelism.md's 2-D rules):

- **pipe collectives move stage boundaries**: one fp32
  ``collective_permute`` per executed activation/cotangent shift,
  priced at full payload on both the measured and static side. The
  host *skips* the shifts whose payload is an all-zeros constant (the
  tick-0 forward recv, the first backward tick's cotangent recv) —
  XLA would fold them away, and a folded op recorded as measured
  would diverge from the static audit.
- **data collectives move gradients** and compress (int8 + error
  feedback scoped to the ``data`` axis); **model collectives move
  activations** and stay fp32 — both exactly as on the 2-D mesh.
- **Edge (embedding / final-LN / LM-head) parameters** are replicated
  over ``pipe``; only their owning stage produces a nonzero gradient,
  and one fp32 psum over ``pipe`` rebroadcasts the true gradient to
  every stage (the tied-embedding idiom) before the DP sync.

Overlap-in-bubbles, stated honestly (the ``parallel/overlap.py``
convention): in one SPMD program the gradient accumulator is a single
tensor last written by the final backward tick, so the per-bucket DP
psums cannot be data-ready *during* earlier cooldown ticks — they are
traced after the final tick as K independent per-bucket collectives
(no chaining; the ``overlap-serialization`` lint rule proves it). On a
real TPU backend the latency-hiding scheduler is then free to overlap
them with the cooldown's trailing backward compute — the bubble slots
— because nothing downstream consumes them until the weight update.
On the 1-core CPU mesh this repo measures on, the win is eliminated
work, same as the 2-D overlapped step: the EF residual stays in the
bucket block domain (no per-step flatten/unflatten marshalling) and
``fold_average`` folds the ``1/dp`` averaging into the dequant scales.
``mode="baseline"`` keeps the identical bucket grid and wire bytes but
carries a leaf-domain residual with per-step marshalling and
divide-after averaging — the measured delta between the two is the
eliminated work, at provably identical per-axis comm bytes.

Elastic story: a ``(dp, tp, pp)`` run's per-stage ZeRO shard tables
consolidate/reshard through ``consolidate_zero_state_3d`` /
``reshard_zero_state_3d`` (contrib.optimizers.distributed_fused_adam),
and the supervisor's shrink policy gives up the *last* tuple axis
first — pipe, then model, then data (docs/resilience.md).

Import layering: this module is imported by the transformer-tree compat
shims *while* ``apex_tpu.transformer`` is mid-initialization, so it
imports nothing from ``apex_tpu.transformer`` or ``apex_tpu.parallel``
at module scope — only jax/numpy and telemetry. All substrate imports
(mesh2d, overlap, compression, resilience, parallel_state) are
function-local.
"""

import time
import warnings
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.telemetry import comm as _telemetry_comm
from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.telemetry.registry import get_registry

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"

# The reference-API schedules below default to the transformer tree's
# 'pp' axis name. Kept as a literal: importing it from
# transformer.parallel_state at module scope would close the import
# cycle transformer/__init__ -> pipeline_parallel -> (shim) -> here.
PIPELINE_PARALLEL_AXIS = "pp"

_MOVED_WARNED = False


def _warn_moved(old_module):
    """One DeprecationWarning per process across BOTH compat shims —
    the first of ``schedules`` / ``p2p_communication`` to be imported
    warns, the second stays silent (same contract as the
    ``contrib._pallas_gate`` retirement pattern)."""
    global _MOVED_WARNED
    if _MOVED_WARNED:
        return
    _MOVED_WARNED = True
    warnings.warn(
        f"{old_module} has moved to apex_tpu.parallel.pipeline; the "
        f"apex_tpu.transformer.pipeline_parallel modules are compat "
        f"shims re-exporting it",
        DeprecationWarning, stacklevel=3)


def _parallel_state():
    # lazy: see the PIPELINE_PARALLEL_AXIS layering note
    from apex_tpu.transformer import parallel_state
    return parallel_state


# ---------------------------------------------------------------------------
# p2p helpers (relocated from transformer.pipeline_parallel.p2p_communication)
# ---------------------------------------------------------------------------

def _perm_fwd(world, circular=False):
    if circular:
        return [(i, (i + 1) % world) for i in range(world)]
    return [(i, i + 1) for i in range(world - 1)]


def _perm_bwd(world, circular=False):
    if circular:
        return [(i, (i - 1) % world) for i in range(world)]
    return [(i + 1, i) for i in range(world - 1)]


def send_forward_recv_forward(output_tensor, axis_name=PIPELINE_PARALLEL_AXIS,
                              world: Optional[int] = None,
                              circular: bool = False):
    """Shift activations one stage forward: rank r's value arrives at r+1;
    rank 0 receives zeros (or rank P-1's value when ``circular``).
    (reference recv_forward + send_forward pair)"""
    world = (world if world is not None
             else _parallel_state().get_pipeline_model_parallel_world_size())
    if world == 1:
        return (output_tensor if circular
                else jax.tree_util.tree_map(jnp.zeros_like, output_tensor))
    perm = _perm_fwd(world, circular)
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm), output_tensor)


def send_backward_recv_backward(input_tensor_grad,
                                axis_name=PIPELINE_PARALLEL_AXIS,
                                world: Optional[int] = None,
                                circular: bool = False):
    """Shift gradients one stage backward: rank r's value arrives at r-1;
    the last rank receives zeros (or rank 0's value when ``circular``)."""
    world = (world if world is not None
             else _parallel_state().get_pipeline_model_parallel_world_size())
    if world == 1:
        return (input_tensor_grad if circular
                else jax.tree_util.tree_map(jnp.zeros_like,
                                            input_tensor_grad))
    perm = _perm_bwd(world, circular)
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm), input_tensor_grad)


# Aliases matching the reference wrapper names
# (fwd_bwd_pipelining_without_interleaving.py:87-240). Under SPMD every
# rank runs the same ppermute, so send and recv are one op.

def recv_forward(output_tensor, **kw):
    return send_forward_recv_forward(output_tensor, **kw)


def send_forward(output_tensor, **kw):
    return send_forward_recv_forward(output_tensor, **kw)


def recv_backward(input_tensor_grad, **kw):
    return send_backward_recv_backward(input_tensor_grad, **kw)


def send_backward(input_tensor_grad, **kw):
    return send_backward_recv_backward(input_tensor_grad, **kw)


def send_forward_recv_backward(output_tensor, input_tensor_grad, **kw):
    return (send_forward_recv_forward(output_tensor, **kw),
            send_backward_recv_backward(input_tensor_grad, **kw))


def send_backward_recv_forward(input_tensor_grad, output_tensor, **kw):
    return (send_backward_recv_backward(input_tensor_grad, **kw),
            send_forward_recv_forward(output_tensor, **kw))


# ---------------------------------------------------------------------------
# reference schedules (relocated from transformer.pipeline_parallel.schedules)
# ---------------------------------------------------------------------------

def listify_model(model):
    if isinstance(model, list):
        return model
    return [model]


def pipeline_schedule_plan(pp_size: int, num_microbatches: int,
                           num_model_chunks: int = 1) -> dict:
    """Static tick/memory plan of the pipelined schedules (pure Python).

    The schedules below derive their loop bounds and stash sizes from this
    function, so its numbers are the numbers — tests assert on them.

    Forward unit k = round*P*V + c*P + j of (chunk c, microbatch
    i = round*P + j) runs on rank r at tick k + r — microbatch groups of
    size P cycling through chunks, the reference's get_model_chunk_id
    order (V=1 degenerates to k = i) — and its backward mirrors it from
    tick V*P - 1 (the last global stage's backward shares its forward's
    tick). Chunk handoffs ride a circular ppermute with exactly-one-tick
    latency, so rank 0's warmup before its first backward is
    2(P-1) + (V-1)*P units, the reference's warmup formula
    (fwd_bwd_pipelining_with_interleaving.py num_warmup_microbatches).
    """
    P, M, V = pp_size, num_microbatches, num_model_chunks
    if V == 1:
        return {
            "warmup": P - 1,            # fwd-only ticks
            "steady": M,                # fwd+bwd ticks
            "cooldown": P - 1,          # bwd-only ticks
            "total": M + 2 * P - 2,
            "fwd_ticks": M + P - 1,     # ticks executing a fwd unit
            "bwd_ticks": M + P - 1,
            "stash": min(M, 2 * P - 1),  # in-flight stage inputs: O(P)
        }
    return {
        "warmup": V * P - 1,
        "steady": M * V,
        "cooldown": P - 1,
        "total": M * V + V * P + P - 2,
        "fwd_ticks": M * V + V * P - 1,
        "bwd_ticks": M * V + P - 1,
        "stash": min(M * V, 2 * V * P),  # O(P*V) chunk-stage inputs
    }


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size=None):
    """Select a schedule (reference schedules/__init__.py:22-35).

    A pipeline split rank installed via ``initialize_model_parallel``
    selects the encoder-decoder schedule (the reference routes
    ``ModelType.encoder_and_decoder`` through the same selector; its
    interleaved schedule is encoder_or_decoder-only, and so is ours)."""
    ps = _parallel_state()
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = \
            ps.get_pipeline_model_parallel_world_size()
    if virtual_pipeline_model_parallel_size is None:
        virtual_pipeline_model_parallel_size = (
            ps.get_virtual_pipeline_model_parallel_world_size())
    if pipeline_model_parallel_size > 1:
        if ps.get_pipeline_model_parallel_split_rank() is not None:
            if virtual_pipeline_model_parallel_size is not None:
                raise ValueError(
                    "interleaved (virtual-pipeline) scheduling does not "
                    "compose with an encoder-decoder split rank")
            return forward_backward_pipelining_with_split
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def forward_backward_no_pipelining(forward_step_func, loss_func, params,
                                   microbatches, *, num_microbatches,
                                   grad_scale=1.0, **unused):
    """Accumulate grads over microbatches without pipelining
    (reference fwd_bwd_no_pipelining.py:23-124; grad sync deferral to the
    last microbatch is automatic — sync happens once on the returned
    accumulated grads)."""

    def one_microbatch(params, mb):
        def full(p):
            y = forward_step_func(p, None, mb, jnp.asarray(True))
            return loss_func(p, y, mb)

        loss, grads = jax.value_and_grad(full)(params)
        return loss, grads

    def scan_body(carry, mb):
        loss_sum, grads_acc = carry
        loss, grads = one_microbatch(params, mb)
        grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
        return (loss_sum + loss, grads_acc), loss

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (loss_sum, grads), losses = lax.scan(
        scan_body, (jnp.zeros((), jnp.float32), zero_grads), microbatches)
    n = jnp.asarray(num_microbatches, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g * (grad_scale / n), grads)
    return losses, grads


def _payload_spec(tensor_shape, dtype):
    """Normalize the boundary-payload description to a pytree of
    ``jax.ShapeDtypeStruct``. A plain tuple/list of ints (the common
    single-activation case) becomes one leaf of ``dtype``; anything else
    is taken as an already-built spec pytree — the encoder-decoder
    schedule passes a two-leaf dict (reference dual shapes,
    ...without_interleaving.py:29-86)."""
    if (isinstance(tensor_shape, (tuple, list))
            and all(isinstance(d, (int, np.integer)) for d in tensor_shape)):
        return jax.ShapeDtypeStruct(
            tuple(int(d) for d in tensor_shape), dtype)
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(tuple(s.shape), s.dtype),
        tensor_shape)


def _pipelined_fwd_bwd(forward_step_func, loss_func, params, microbatches,
                       *, M, V, P, tensor_shape, dtype, axis_name,
                       grad_scale, aux_loss=False):
    """Shared 3-phase tick machine for both pipelined schedules
    (see pipeline_schedule_plan for the tick/unit mapping).

    The stage-boundary payload is a pytree (single activation array for
    GPT-style stacks; an {encoder, decoder} pair for split-rank models);
    every payload op below — stash, ppermute shift, masking, dtype cast —
    is tree-mapped over its leaves.

    ``aux_loss=True`` changes the stage contract to
    ``forward_step_func(...) -> (output_tensor, aux_scalar)``: each
    unit's backward injects its own stage's auxiliary loss (e.g. MoE
    router load-balancing, scaled by grad_scale like the main loss)
    alongside the downstream activation cotangent — total loss =
    last-stage loss_func + sum of per-unit aux, with aux gradients
    flowing to earlier stages through the regular backward wave. The
    reported per-microbatch losses remain the last stage's (loss_func +
    its own aux) only.
    """
    plan = pipeline_schedule_plan(P, M, V)
    S = plan["stash"]
    PV, MV = P * V, M * V
    T0 = V * P - 1  # first backward tick (mb 0 has crossed all V*P stages)
    rank = lax.axis_index(axis_name)
    interleaved = V > 1
    tmap = jax.tree_util.tree_map
    spec = _payload_spec(tensor_shape, dtype)

    def _mask(pred, tree):
        return tmap(lambda a: jnp.where(pred, a, jnp.zeros_like(a)), tree)

    def _select(pred, tree_a, tree_b):
        return tmap(lambda a, b: jnp.where(pred, a, b), tree_a, tree_b)

    def _cast(tree):
        return tmap(lambda a, s: a.astype(s.dtype), tree, spec)

    def take_mb(i):
        return jax.tree_util.tree_map(lambda a: a[i], microbatches)

    if interleaved:
        def take_params(c):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                params)

        def add_grads(grads, dp, c, active):
            return jax.tree_util.tree_map(
                lambda a, d: a.at[c].add(
                    jnp.where(active, d.astype(jnp.float32), 0.0)),
                grads, dp)
    else:
        def take_params(c):
            return params

        def add_grads(grads, dp, c, active):
            return jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(active, d.astype(jnp.float32),
                                           0.0),
                grads, dp)

    def fwd_unit(k):
        rnd, rem = k // PV, k % PV
        c, j = rem // P, rem % P
        return c, rnd * P + j, k % S

    def bwd_unit(kb):
        rnd, rem = kb // PV, kb % PV
        c, j = (V - 1) - rem // P, rem % P
        kf = rnd * PV + c * P + j
        return c, rnd * P + j, kf % S

    zero_h = tmap(lambda s: jnp.zeros(s.shape, s.dtype), spec)

    def run_stage(p, h, mb, is_first_u):
        if aux_loss:
            return forward_step_func(p, h, mb, is_first_u)
        return (forward_step_func(p, h, mb, is_first_u),
                jnp.zeros((), jnp.float32))

    def stage_and_maybe_loss(p, h, mb, is_first_u, is_last_u):
        y, aux = run_stage(p, h, mb, is_first_u)
        # Only the last global stage pays for loss_func (for GPT: the
        # vocab projection) — lax.cond skips it at runtime elsewhere, in
        # both the primal and the transpose. Per-unit aux (module doc)
        # rides the same loss output.
        loss = lax.cond(
            is_last_u,
            lambda op: loss_func(*op).astype(jnp.float32),
            lambda op: jnp.zeros((), jnp.float32),
            (p, y, mb))
        return y, loss + aux.astype(jnp.float32)

    # state = (stash, y_prev, dx_prev, losses, grads)
    def fwd_half(t, state):
        with jax.named_scope("pp_fwd_unit"):
            xs, y_prev, dx_prev, losses, grads = state
            recv = send_forward_recv_forward(
                y_prev, axis_name, world=P, circular=interleaved)
            k = t - rank
            active = (k >= 0) & (k < MV)
            c, i, slot = fwd_unit(jnp.clip(k, 0, MV - 1))
            mb = take_mb(i)
            p_c = take_params(c)
            is_first_u = (rank == 0) & (c == 0)
            h_in = _cast(_select(is_first_u, zero_h, recv))
            y, _ = run_stage(p_c, h_in, mb, is_first_u)
            xs = tmap(
                lambda buf, h: lax.dynamic_update_index_in_dim(
                    buf, jnp.where(active, h, buf[slot]), slot, 0),
                xs, h_in)
            y_prev = _mask(active, y)
            return xs, y_prev, dx_prev, losses, grads

    def bwd_half(t, state):
        with jax.named_scope("pp_bwd_unit"):
            xs, y_prev, dx_prev, losses, grads = state
            dy_recv = send_backward_recv_backward(
                dx_prev, axis_name, world=P, circular=interleaved)
            kb = t - T0 - (P - 1 - rank)
            active = (kb >= 0) & (kb < MV)
            c, i, slot = bwd_unit(jnp.clip(kb, 0, MV - 1))
            mb = take_mb(i)
            p_c = take_params(c)
            is_first_u = (rank == 0) & (c == 0)
            is_last_u = (rank == P - 1) & (c == V - 1)
            # the last global stage's backward shares its forward's tick,
            # and fwd_half runs first in a steady tick, so the slot read
            # here is the input stashed moments ago; other reads never
            # collide with this tick's write (ring size >= in-flight).
            h_in = tmap(lambda buf: buf[slot], xs)
            (_, loss), pullback = jax.vjp(
                lambda p, h: stage_and_maybe_loss(p, h, mb, is_first_u,
                                                  is_last_u), p_c, h_in)
            dy_cot = _cast(_mask(active & ~is_last_u, dy_recv))
            # every active unit gets a loss cotangent: the main loss is
            # cond-gated to the last stage (zero transpose elsewhere),
            # while per-unit aux losses (if any) pick it up on their
            # own stage
            loss_cot = jnp.where(active,
                                 jnp.asarray(grad_scale, jnp.float32), 0.0)
            dp_c, dh = pullback((dy_cot, loss_cot))
            grads = add_grads(grads, dp_c, c, active)
            losses = losses.at[i].add(
                jnp.where(active & is_last_u, loss, 0.0))
            dx_prev = _cast(_mask(active, dh))
            return xs, y_prev, dx_prev, losses, grads

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    stash0 = tmap(lambda s: jnp.zeros((S,) + tuple(s.shape), s.dtype), spec)
    state = (stash0, zero_h, zero_h,
             jnp.zeros((M,), jnp.float32), zero_grads)
    w, s = plan["warmup"], plan["steady"]
    state = lax.fori_loop(0, w, fwd_half, state)
    state = lax.fori_loop(w, w + s,
                          lambda t, st: bwd_half(t, fwd_half(t, st)), state)
    state = lax.fori_loop(w + s, plan["total"], bwd_half, state)
    _, _, _, losses, grads = state
    n = jnp.asarray(M, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g / n, grads)
    return losses, grads


def forward_backward_pipelining_without_interleaving(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int,
        tensor_shape, dtype=jnp.float32,
        axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0,
        pp_size: Optional[int] = None,
        aux_loss: bool = False,
        **unused):
    """True 1F1B over the 'pp' axis in one jitted program (see module doc).

    Parity target: fwd_bwd_pipelining_without_interleaving.py:241-597.
    Returns (per-microbatch losses [M] — nonzero on the last stage only,
    grads pytree scaled by grad_scale / num_microbatches).

    Must run inside shard_map with the 'pp' axis bound; ``tensor_shape``
    is the (seq, microbatch, hidden) activation shape crossing stage
    boundaries (reference get_tensor_shapes,
    ...without_interleaving.py:29-86).
    """
    P = pp_size or _parallel_state().get_pipeline_model_parallel_world_size()
    return _pipelined_fwd_bwd(
        forward_step_func, loss_func, params, microbatches,
        M=num_microbatches, V=1, P=P, tensor_shape=tensor_shape,
        dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
        aux_loss=aux_loss)


def forward_backward_pipelining_with_interleaving(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int, tensor_shape,
        dtype=jnp.float32, axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0, pp_size: Optional[int] = None,
        num_model_chunks: Optional[int] = None, aux_loss: bool = False,
        **unused):
    """Interleaved (virtual-pipeline) 1F1B in one steady state.

    Parity target: fwd_bwd_pipelining_with_interleaving.py (516 LoC).
    ``params`` is a pytree whose leaves carry a leading ``num_model_chunks``
    dim (stacked virtual chunks per rank); chunk c on rank r is global
    stage c * P + r. Unlike a sequential-passes scheme (bubble V*(P-1)
    full passes), all chunks share ONE steady state: each global tick maps
    to a (chunk, microbatch) unit per rank via the reference's
    get_model_chunk_id order, so the forward wave fills in V*P - 1 ticks
    and drains in P - 1 — per-rank overhead (V*P-1) fwd units + (P-1) bwd
    units over the M*V useful ticks, matching the reference's rank-0
    warmup of 2(P-1) + (V-1)P forward units. Chunk handoffs (rank P-1's
    chunk-c output -> rank 0's chunk c+1 input, and the reverse for
    grads) have exactly-one-tick latency under this order, so they ride
    the same *circular* ppermute as the intra-chunk shifts — no boundary
    buffers.
    """
    ps = _parallel_state()
    P = pp_size or ps.get_pipeline_model_parallel_world_size()
    V = (num_model_chunks
         or ps.get_virtual_pipeline_model_parallel_world_size() or 1)
    if V == 1:
        return forward_backward_pipelining_without_interleaving(
            forward_step_func, loss_func, params, microbatches,
            num_microbatches=num_microbatches, tensor_shape=tensor_shape,
            dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
            pp_size=P, aux_loss=aux_loss)
    if num_microbatches % P != 0:
        # reference fwd_bwd_pipelining_with_interleaving.py asserts
        # num_microbatches % pipeline_parallel_size == 0
        raise ValueError(
            f"interleaved schedule requires num_microbatches "
            f"({num_microbatches}) to be a multiple of "
            f"pipeline_model_parallel_size ({P})")
    return _pipelined_fwd_bwd(
        forward_step_func, loss_func, params, microbatches,
        M=num_microbatches, V=V, P=P, tensor_shape=tensor_shape,
        dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
        aux_loss=aux_loss)


def forward_backward_pipelining_with_split(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int,
        encoder_tensor_shape, decoder_tensor_shape,
        dtype=jnp.float32, axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0, pp_size: Optional[int] = None,
        split_rank: Optional[int] = None, aux_loss: bool = False,
        **unused):
    """Encoder-decoder (split-rank) 1F1B.

    Parity target: the reference's ``ModelType.encoder_and_decoder`` path —
    dual p2p tensor shapes computed from ``decoder_seq_length``
    (fwd_bwd_pipelining_without_interleaving.py:29-86's get_tensor_shapes)
    with the encoder on ranks ``< split_rank`` and the decoder at/after it
    (parallel_state.py:243-331 places embedding groups around the same
    split). The reference moves *two* tensors across decoder-side stage
    boundaries (encoder memory + decoder stream); here the boundary
    payload is the two-leaf pytree
    ``{"encoder": (enc_seq, mb, h), "decoder": (dec_seq, mb, h)}`` riding
    the same tick machine — encoder ranks advance the encoder leaf and
    pass the decoder leaf through untouched; decoder ranks advance the
    decoder leaf with the encoder leaf as cross-attention memory,
    forwarding it unchanged so every decoder stage sees the final encoder
    output. Interleaving is not supported with a split (matches the
    reference's encoder_or_decoder-only interleaved schedule).

    Stage contract (build with :func:`make_encoder_decoder_step`):

        forward_step_func(params, payload_dict, mb, is_first_stage)
            -> payload_dict
        loss_func(params, payload_dict, mb) -> scalar   # reads "decoder"

    Returns (per-microbatch losses [M] — nonzero on the last stage only,
    grads pytree scaled by grad_scale / num_microbatches).
    """
    P = pp_size or _parallel_state().get_pipeline_model_parallel_world_size()
    split = (split_rank if split_rank is not None
             else _parallel_state().get_pipeline_model_parallel_split_rank())
    if split is None or not 0 < split < P:
        raise ValueError(
            f"encoder-decoder pipelining needs 0 < split_rank < pp_size; "
            f"got split_rank={split}, pp_size={P} (set it via "
            f"initialize_model_parallel(..., "
            f"pipeline_model_parallel_split_rank=...) or pass split_rank=)")
    spec = {
        "encoder": jax.ShapeDtypeStruct(tuple(encoder_tensor_shape), dtype),
        "decoder": jax.ShapeDtypeStruct(tuple(decoder_tensor_shape), dtype),
    }
    return _pipelined_fwd_bwd(
        forward_step_func, loss_func, params, microbatches,
        M=num_microbatches, V=1, P=P, tensor_shape=spec, dtype=dtype,
        axis_name=axis_name, grad_scale=grad_scale, aux_loss=aux_loss)


def make_encoder_decoder_step(encoder_step: Callable, decoder_step: Callable,
                              *, split_rank: Optional[int] = None,
                              axis_name: str = PIPELINE_PARALLEL_AXIS):
    """Build the stage fn for :func:`forward_backward_pipelining_with_split`
    from per-side step functions:

        encoder_step(params, enc_h, mb, is_first_stage) -> enc_h
            (build enc_h from the microbatch when is_first_stage)
        decoder_step(params, dec_h, enc_memory, mb, is_split_stage) -> dec_h
            (build dec_h from the microbatch when is_split_stage — the
            first decoder stage, where the upstream decoder leaf is zeros)

    Rank-side selection is a runtime ``lax.cond`` on the pp mesh position
    vs the split rank — one SPMD program, each rank executes only its own
    side (consuming the split-rank bookkeeping the reference keeps in
    parallel_state.py:469-486 / is_pipeline_stage_before_split).
    ``params`` must carry both sides' weights in a uniform pytree on every
    rank (each rank's unused side receives zero grads).
    """
    split = (split_rank if split_rank is not None
             else _parallel_state().get_pipeline_model_parallel_split_rank())
    if split is None:
        raise ValueError("make_encoder_decoder_step needs a split rank")

    def step(params, payload, mb, is_first_stage):
        rank = lax.axis_index(axis_name)

        def enc_branch(op):
            p, pl, mb_, first = op
            return {"encoder": encoder_step(p, pl["encoder"], mb_, first),
                    "decoder": pl["decoder"]}

        def dec_branch(op):
            p, pl, mb_, _ = op
            return {"encoder": pl["encoder"],
                    "decoder": decoder_step(p, pl["decoder"], pl["encoder"],
                                            mb_, rank == split)}

        return lax.cond(rank >= split, dec_branch, enc_branch,
                        (params, payload, mb, is_first_stage))

    return step


# ---------------------------------------------------------------------------
# the 3-D (data, model, pipe) mesh
# ---------------------------------------------------------------------------

def mesh_3d(data=2, model=2, pipe=None, devices=None):
    """The named 3-D ``(data, model, pipe)`` mesh: ``data`` planes of
    ``model`` x ``pipe`` tiles over the first ``data * model * pipe``
    devices (default: all of them,
    ``pipe = len(devices) // (data * model)``)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if pipe is None:
        if len(devices) % (data * model) != 0:
            raise ValueError(
                f"mesh_3d: {len(devices)} devices do not split into "
                f"data={data} x model={model} planes")
        pipe = len(devices) // (data * model)
    need = data * model * pipe
    if len(devices) < need:
        raise ValueError(
            f"mesh_3d: need {need} devices (data={data} x model={model} "
            f"x pipe={pipe}), have {len(devices)}")
    return Mesh(np.asarray(devices[:need]).reshape(data, model, pipe),
                (DATA_AXIS, MODEL_AXIS, PIPE_AXIS))


def analytic_bubble_fraction(pp, microbatches):
    """The 1F1B bubble model: of ``m + pp - 1`` pipeline slots per
    phase, ``pp - 1`` are idle — fraction ``(pp-1)/(m+pp-1)``
    (docs/parallelism.md has the derivation and the measured
    comparison)."""
    return (pp - 1) / float(microbatches + pp - 1)


def schedule_ticks(pp, microbatches):
    """Host-side 1F1B tick table — the Python mirror of the tick machine
    (V=1): per tick, which (rank, microbatch) forward/backward units
    execute. :func:`build_pipeline_step` drives its unrolled loop off
    this table and stamps each tick's entry onto its ``pp_tick_<t>``
    telemetry span, which is what ``tools/telemetry_report.py`` renders
    as the per-stage microbatch timeline."""
    plan = pipeline_schedule_plan(pp, microbatches)
    w, s, total = plan["warmup"], plan["steady"], plan["total"]
    T0 = pp - 1
    ticks = []
    for t in range(total):
        fwd = [[r, t - r] for r in range(pp)
               if t < w + s and 0 <= t - r < microbatches]
        bwd = [[r, t - T0 - (pp - 1 - r)] for r in range(pp)
               if t >= w and 0 <= t - T0 - (pp - 1 - r) < microbatches]
        phase = ("warmup" if t < w
                 else "steady" if t < w + s else "cooldown")
        ticks.append({"tick": t, "phase": phase, "fwd": fwd, "bwd": bwd})
    return ticks


# ---------------------------------------------------------------------------
# stage-partitioned GPT-2 parameter layout
# ---------------------------------------------------------------------------

def split_stages(seg_params, pp):
    """Partition the mesh2d segment tuple into ``pp`` contiguous stages
    of ``layers // pp`` layers each."""
    layers = len(seg_params)
    if layers % pp:
        raise ValueError(
            f"{layers} layers do not split into pp={pp} stages")
    lp = layers // pp
    return ([tuple(seg_params[s * lp:(s + 1) * lp]) for s in range(pp)],
            lp)


def stack_stage_blocks(seg_params, pp):
    """``(blocks, edge)``: the transformer block params stacked to
    leaves ``[pp, Lp, ...]`` (stage-sharded over ``pipe``, TP dims over
    ``model``) plus the ``edge`` dict — embedding tables, final LN, LM
    head — replicated on every rank (only the owning stage computes
    with them; a pipe psum rebroadcasts their gradients)."""
    stages, _ = split_stages(seg_params, pp)
    per_stage = []
    for stage in stages:
        layer_dicts = [seg["layer"] for seg in stage]
        per_stage.append(jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *layer_dicts))
    blocks = jax.tree_util.tree_map(lambda *ss: jnp.stack(ss), *per_stage)
    edge = {"embed": seg_params[0]["embed"],
            "ln_f": seg_params[-1]["ln_f"],
            "head": seg_params[-1]["head"]}
    return blocks, edge


def pipeline_zero_segments(seg_params):
    """``(segments, partition_dims)`` in the pipeline ZeRO convention:
    one segment per transformer layer in model order plus the
    pipe-replicated edge LAST — the ``params``/``partition_dims``
    inputs of :func:`~apex_tpu.contrib.optimizers.
    distributed_fused_adam.consolidate_zero_state_3d` (and its
    reshard inverse) with ``shared_tail=1``. Matches the segment
    layout :func:`build_pipeline_step`'s DP sync buckets are planned
    over, so per-stage optimizer states line up leaf-for-leaf."""
    from apex_tpu.parallel.mesh2d import gpt2_partition_dims

    _, edge = stack_stage_blocks(seg_params, 1)
    segments = [seg["layer"] for seg in seg_params] + [edge]
    return segments, gpt2_partition_dims(segments)


def pipeline_block_pspecs(blocks):
    """PartitionSpecs for the stacked block leaves: dim 0 (stage) over
    ``pipe``, the mesh2d TP partition dim (shifted by the two stacking
    dims) over ``model``, replicated over ``data``."""
    from apex_tpu.parallel.mesh2d import _COL_B, _COL_W, _ROW_W, _leaf_name

    def spec(path, leaf):
        name = _leaf_name(path)
        if name in _COL_W:
            return P(PIPE_AXIS, None, None, MODEL_AXIS)
        if name in _COL_B or name in _ROW_W:
            return P(PIPE_AXIS, None, MODEL_AXIS)
        return P(PIPE_AXIS)

    return jax.tree_util.tree_map_with_path(spec, blocks)


def place_pipeline_state(mesh, blocks, edge, *extra):
    """Commit the stacked blocks to their ``NamedSharding`` placement
    and the edge + every extra carry tree to the replicated sharding —
    one compiled signature for the first call and the steady state
    (the mesh2d ``place_state`` discipline, including the copy-before-
    device_put donation-aliasing guard)."""
    from apex_tpu.parallel.mesh2d import _norm_spec

    bspecs = jax.tree_util.tree_map(lambda s: _norm_spec(s, mesh),
                                    pipeline_block_pspecs(blocks))
    fresh = jax.tree_util.tree_map(jnp.copy, blocks)
    placed = jax.device_put(
        fresh,
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), bspecs))
    rep = NamedSharding(mesh, P())
    return (placed,) + tuple(
        jax.device_put(jax.tree_util.tree_map(jnp.copy, t), rep)
        for t in (edge,) + extra)


def make_batch_3d(mesh, *, microbatches, batch_per_replica=2, seq=16,
                  vocab=64, seed=1):
    """Token/label batch sharded over ``data`` (replicated over
    ``model`` and ``pipe``): ``microbatches * batch_per_replica`` rows
    per data rank, reshaped to ``[M, b, seq]`` inside the step."""
    rng = np.random.RandomState(seed)
    rows = microbatches * batch_per_replica * mesh.shape[DATA_AXIS]
    tokens = jnp.asarray(rng.randint(0, vocab, (rows, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, vocab, (rows, seq)), jnp.int32)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    return jax.device_put((tokens, labels), sharding)


# ---------------------------------------------------------------------------
# the host-driven 1F1B train step
# ---------------------------------------------------------------------------

def build_pipeline_step(mesh, seg_params, *, hidden, heads, microbatches,
                        mode="overlapped", compress="int8", lr=0.05,
                        fold_average=True, message_size=10000000,
                        guard_nan=None, straggler=None, donate=True):
    """One jitted 3-D ``(data, model, pipe)`` train step.

    The schedule is the same 1F1B tick math as the reference machine
    (:func:`_pipelined_fwd_bwd` at V=1), host-unrolled over
    :func:`schedule_ticks` — per-tick ``pp_tick_<t>`` spans, one
    recorded ``collective_permute`` per *executed* stage shift (the
    all-zeros tick-0 forward recv and first-backward cotangent recv are
    skipped, see module doc), and the DP bucket psums traced into the
    cooldown region.

    ``mode="overlapped"``: bucket-domain EF residual, ``fold_average``,
    per-bucket DP psums emitted as independent collectives after the
    final backward tick — ``step(blocks, edge, res, tokens, labels) ->
    (blocks, edge, res, loss)``.

    ``mode="baseline"``: identical bucket grid and wire bytes, but a
    leaf-domain residual with per-step flatten/pad marshalling and
    divide-after averaging — same signature.

    ``mode="guarded"``: the overlapped step under
    ``resilience.guarded_update`` with the local non-finite flag OR'd
    over ALL THREE axes — every ``(data, model, pipe)`` coordinate must
    agree to commit — ``step(blocks, edge, res, gst, step_idx, tokens,
    labels) -> (blocks, edge, res, gst, loss)``. ``guard_nan=(step,
    stage, microbatch)`` arms ``faults.inject_nan`` at that exact
    schedule unit's stage input.

    ``straggler=(stage, delay_s)`` is the trace-time straggler fault
    for the online attribution acceptance
    (``telemetry.attribution``): every tick in which ``stage`` has a
    forward or backward unit sleeps ``delay_s`` host seconds inside
    its ``pp_tick_<t>`` span. The sleep happens while the schedule is
    being *traced* — the compiled program is unchanged — so the
    measured span deltas carry a genuine per-stage slowdown that the
    exposure-difference estimator must name.

    Returns ``(jitted_step, state)`` where ``state`` is the placed
    carry tuple (blocks, edge, residual[, guard state]).
    """
    from apex_tpu import resilience
    from apex_tpu.parallel import compression, mesh2d
    from apex_tpu.parallel.distributed import flatten, unflatten
    from apex_tpu.parallel.overlap import OverlappedDataParallel
    from apex_tpu.resilience import faults
    from apex_tpu.resilience.guard import nonfinite_flag

    head_dim = hidden // heads
    dp = mesh.shape[DATA_AXIS]
    tp = mesh.shape[MODEL_AXIS]
    pp = mesh.shape[PIPE_AXIS]
    _, lp = split_stages(seg_params, pp)
    M = int(microbatches)
    plan3 = pipeline_schedule_plan(pp, M)
    w, s, total = plan3["warmup"], plan3["steady"], plan3["total"]
    S, T0 = plan3["stash"], pp - 1
    ticks = schedule_ticks(pp, M)
    if mode not in ("baseline", "overlapped", "guarded"):
        raise ValueError(f"unknown mode {mode!r}")

    blocks, edge = stack_stage_blocks(seg_params, pp)
    bspecs = pipeline_block_pspecs(blocks)

    # DP sync segments: one per layer (every stage's layer l shares
    # shapes, so one LOCAL per-model-rank template serves all) plus the
    # edge — buckets never span a layer/edge boundary.
    layer_local = mesh2d.local_template(seg_params[0]["layer"], tp)
    edge_local = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, a.dtype), edge)
    seg_templates = [layer_local] * lp + [edge_local]

    odp = OverlappedDataParallel(
        axis_name=DATA_AXIS, compress=compress,
        fold_average=(fold_average and mode != "baseline"),
        message_size=message_size)
    plan = odp.plan(seg_templates)
    stateful = compression.needs_residual(compress)
    if not stateful:
        residual = jnp.zeros(())
    elif mode == "baseline":
        # leaf-domain EF state — the honest marshalling baseline
        residual = tuple(jax.tree_util.tree_map(jnp.copy, t)
                         for t in seg_templates)
    else:
        residual = odp.init_residual(seg_templates)

    def run_pipeline(lb, eP, tokens, labels, step_idx=None):
        """The unrolled 1F1B schedule on LOCAL shards. ``lb`` leaves are
        the ``[Lp, ...local]`` stage view; returns ``(gB, gE, loss)``
        with grads already divided by M, edge grads pipe-psummed, and
        the scalar loss reduced over pipe and data."""
        rank = lax.axis_index(PIPE_AXIS)
        is_first = rank == 0
        is_last = rank == pp - 1
        b = tokens.shape[0] // M
        seq_len = tokens.shape[1]
        tok3 = tokens.reshape(M, b, seq_len)
        lab3 = labels.reshape(M, b, seq_len)
        reg = get_registry()
        if reg.enabled:
            reg.event("pipeline", "plan", stages=pp, microbatches=M,
                      warmup=w, steady=s, cooldown=plan3["cooldown"],
                      total=total, stash=S)

        def stage_fwd(lbv, ev, h_in, tok, i):
            x0 = ev["embed"]["wte"][tok] + ev["embed"]["wpe"][:seq_len]
            x = jnp.where(is_first, x0, h_in)
            if guard_nan is not None:
                gstep, gstage, gmb = guard_nan
                nanval = faults.inject_nan(
                    jnp.zeros((), jnp.float32), step_idx, nan_step=gstep)
                # where, not multiply: NaN-safe off the target unit
                x = x + jnp.where((rank == gstage) & (i == gmb),
                                  nanval, 0.0)
            for layer_i in range(lp):
                pl = jax.tree_util.tree_map(
                    lambda a, li=layer_i: a[li], lbv)
                x = mesh2d._block(pl, x, head_dim)
            return x

        def stage_and_loss(lbv, ev, h_in, tok, lab, i):
            x = stage_fwd(lbv, ev, h_in, tok, i)

            def last_loss(op):
                xv, ev_, lab_ = op
                xn = mesh2d._ln(ev_["ln_f"], xv)
                return mesh2d._xent(xn @ ev_["head"]["w"], lab_)

            loss = lax.cond(is_last, last_loss,
                            lambda op: jnp.zeros((), jnp.float32),
                            (x, ev, lab))
            return x, loss

        zero_h = jnp.zeros((b, seq_len, hidden), jnp.float32)
        stash = jnp.zeros((S, b, seq_len, hidden), jnp.float32)
        y_prev = zero_h
        dx_prev = zero_h
        losses = jnp.zeros((M,), jnp.float32)
        gB = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), lb)
        gE = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), eP)
        h_elems = b * seq_len * hidden
        fwd_perm = _perm_fwd(pp)
        bwd_perm = _perm_bwd(pp)

        def shift(arr, perm):
            _telemetry_comm.record_collective(
                "ppermute", elements=h_elems, dtype=jnp.float32,
                axis_name=PIPE_AXIS)
            return lax.ppermute(arr, PIPE_AXIS, perm)

        def take(a3, i):
            return lax.dynamic_index_in_dim(a3, i, 0, keepdims=False)

        one = jnp.asarray(1.0, jnp.float32)
        zero = jnp.asarray(0.0, jnp.float32)
        for tk in ticks:
            t = tk["tick"]
            with _telemetry_trace.span(
                    f"pp_tick_{t}", role="tick", phase=tk["phase"],
                    tick=t, fwd=tk["fwd"], bwd=tk["bwd"]):
                if straggler is not None:
                    s_stage, s_delay = straggler
                    if any(u[0] == s_stage
                           for u in tk["fwd"] + tk["bwd"]):
                        time.sleep(float(s_delay))
                if t < w + s:  # ------------------------ forward half
                    if pp > 1 and t >= 1:
                        # tick 0's upstream is an all-zeros constant:
                        # the host skips the shift XLA would fold away,
                        # keeping measured counters == the static audit
                        y_recv = shift(y_prev, fwd_perm)
                    else:
                        y_recv = zero_h
                    k = t - rank
                    active = (k >= 0) & (k < M)
                    i = jnp.clip(k, 0, M - 1)
                    slot = i % S
                    y = stage_fwd(lb, eP, y_recv, take(tok3, i), i)
                    stash = lax.dynamic_update_index_in_dim(
                        stash,
                        jnp.where(active, y_recv, take(stash, slot)),
                        slot, 0)
                    y_prev = jnp.where(active, y, 0.0)
                if t >= w:  # --------------------------- backward half
                    if pp > 1 and t >= w + 1:
                        dy_recv = shift(dx_prev, bwd_perm)
                    else:
                        dy_recv = zero_h
                    kb = t - T0 - (pp - 1 - rank)
                    active_b = (kb >= 0) & (kb < M)
                    ib = jnp.clip(kb, 0, M - 1)
                    slot_b = ib % S
                    tok = take(tok3, ib)
                    lab = take(lab3, ib)
                    h_in = take(stash, slot_b)
                    (_, loss_u), pull = jax.vjp(
                        lambda lb_, e_, h_: stage_and_loss(
                            lb_, e_, h_, tok, lab, ib), lb, eP, h_in)
                    dy_cot = jnp.where(active_b & (~is_last),
                                       dy_recv, 0.0)
                    loss_cot = jnp.where(active_b, one, zero)
                    d_lb, d_e, dh = pull((dy_cot, loss_cot))
                    gB = jax.tree_util.tree_map(
                        lambda a, d: a + jnp.where(active_b, d, 0.0),
                        gB, d_lb)
                    gE = jax.tree_util.tree_map(
                        lambda a, d: a + jnp.where(active_b, d, 0.0),
                        gE, d_e)
                    losses = losses.at[ib].add(
                        jnp.where(active_b & is_last, loss_u, 0.0))
                    dx_prev = jnp.where(active_b, dh, 0.0)

        gB = jax.tree_util.tree_map(lambda a: a / M, gB)
        gE = jax.tree_util.tree_map(lambda a: a / M, gE)
        if pp > 1:
            # tied-edge psum: only the owning stage produced a nonzero
            # grad; the sum rebroadcasts it so replicated edge copies
            # stay identical after the update
            edge_elems = sum(int(a.size)
                             for a in jax.tree_util.tree_leaves(gE))
            _telemetry_comm.record_collective(
                "psum", elements=edge_elems, dtype=jnp.float32,
                axis_name=PIPE_AXIS)
            gE = lax.psum(gE, PIPE_AXIS)
            _telemetry_comm.record_collective(
                "psum", elements=M, dtype=jnp.float32,
                axis_name=PIPE_AXIS)
            losses = lax.psum(losses, PIPE_AXIS)
        loss = jnp.sum(losses) / M
        if dp > 1:
            _telemetry_comm.record_collective(
                "psum", elements=1, dtype=jnp.float32,
                axis_name=DATA_AXIS)
            loss = lax.psum(loss, DATA_AXIS) / dp
        return gB, gE, loss

    def dp_sync(gB, gE, res):
        """The per-bucket DP psums, traced into the cooldown region —
        K independent collectives (module doc), each in its
        ``ddp_overlap_bucket_<n>`` span with ``bubble=True``. Returns
        ``(syncedB stacked [Lp, ...], syncedE, new_res)``."""
        seg_grads = [jax.tree_util.tree_map(
            lambda a, li=layer_i: a[li], gB) for layer_i in range(lp)]
        seg_grads.append(gE)
        K = lp + 1
        reg = get_registry()
        if reg.enabled:
            reg.event("overlap", "plan", segments=K,
                      buckets=[len(sg) for sg in plan],
                      compress=compress or "none",
                      fold_average=bool(odp.fold_average),
                      pipeline=True)
        synced = [None] * K
        new_res = [None] * K
        seq_no = 0
        bucket_no = sum(len(sg) for sg in plan)
        for k in reversed(range(K)):
            leaves, treedef = jax.tree_util.tree_flatten(seg_grads[k])
            out_leaves = list(leaves)
            if stateful and mode == "baseline":
                rl, rdef = jax.tree_util.tree_flatten(res[k])
                new_rl = list(rl)
            seg_res = []
            bucket_no -= len(plan[k])
            for bi, bucket in enumerate(plan[k]):
                n = bucket_no + bi
                with _telemetry_trace.span(
                        f"ddp_overlap_bucket_{n}", role="bucket",
                        segment=k, seq=seq_no, elements=bucket.n,
                        bubble=True):
                    flat = flatten([leaves[i] for i in bucket.leaf_idx])
                    if not stateful:
                        r2d = None
                    elif mode == "baseline":
                        # marshal the leaf-domain residual into the
                        # block grid (the per-step cost the overlapped
                        # mode eliminates)
                        r2d = compression.pad_to_blocks(
                            flatten([rl[i] for i in bucket.leaf_idx]),
                            odp.compress_block_size)
                    else:
                        r2d = res[k][bi]
                    out, err = odp._sync_flat(flat, r2d)
                    for i, piece in zip(
                            bucket.leaf_idx,
                            unflatten(out, [leaves[i]
                                            for i in bucket.leaf_idx])):
                        out_leaves[i] = piece
                    if stateful and mode == "baseline":
                        err_flat = err.reshape(-1)[:bucket.n]
                        for i, piece in zip(
                                bucket.leaf_idx,
                                unflatten(err_flat,
                                          [rl[i]
                                           for i in bucket.leaf_idx])):
                            new_rl[i] = piece
                    else:
                        seg_res.append(err)
                seq_no += 1
            synced[k] = jax.tree_util.tree_unflatten(treedef, out_leaves)
            if stateful and mode == "baseline":
                new_res[k] = jax.tree_util.tree_unflatten(rdef, new_rl)
            else:
                new_res[k] = tuple(seg_res)
        syncedB = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *synced[:lp])
        syncedE = synced[lp]
        if not stateful:
            return syncedB, syncedE, res
        return syncedB, syncedE, tuple(new_res)

    def _view(bl):
        return jax.tree_util.tree_map(lambda a: a[0], bl)

    def _unview(bl):
        return jax.tree_util.tree_map(lambda a: a[None], bl)

    def _apply(lb, eP, sB, sE):
        return (jax.tree_util.tree_map(lambda a, g: a - lr * g, lb, sB),
                jax.tree_util.tree_map(lambda a, g: a - lr * g, eP, sE))

    if mode == "guarded":
        def fn(bl, eP, res, gst, step_idx, tokens, labels):
            lb = _view(bl)
            gB, gE, loss = run_pipeline(lb, eP, tokens, labels,
                                        step_idx=step_idx)
            # flag from the LOCAL pre-compression grads: an int8 psum
            # can launder a NaN into finite wire garbage
            flag = nonfinite_flag((gB, gE))
            sB, sE, new_res = dp_sync(gB, gE, res)

            def commit(g, st):
                sB_, sE_, r_ = g
                lb_, e_, _ = st
                nlb, ne = _apply(lb_, e_, sB_, sE_)
                return (nlb, ne, r_)

            (new_lb, new_e, out_res), gst = resilience.guarded_update(
                (sB, sE, new_res), commit, (lb, eP, res), gst,
                axis_name=(DATA_AXIS, MODEL_AXIS, PIPE_AXIS), flag=flag)
            return _unview(new_lb), new_e, out_res, gst, loss

        in_specs = (bspecs, P(), P(), P(), P(), P(DATA_AXIS),
                    P(DATA_AXIS))
        out_specs = (bspecs, P(), P(), P(), P())
        donate_argnums = (0, 1, 2, 3) if donate else ()
        state = place_pipeline_state(mesh, blocks, edge, residual,
                                     resilience.init_guard_state())
    else:
        def fn(bl, eP, res, tokens, labels):
            lb = _view(bl)
            gB, gE, loss = run_pipeline(lb, eP, tokens, labels)
            sB, sE, new_res = dp_sync(gB, gE, res)
            new_lb, new_e = _apply(lb, eP, sB, sE)
            return _unview(new_lb), new_e, new_res, loss

        in_specs = (bspecs, P(), P(), P(DATA_AXIS), P(DATA_AXIS))
        out_specs = (bspecs, P(), P(), P())
        donate_argnums = (0, 1, 2) if donate else ()
        state = place_pipeline_state(mesh, blocks, edge, residual)

    step = jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False),
        donate_argnums=donate_argnums)
    return step, state
