"""LARC — Layer-wise Adaptive Rate Clipping/Scaling.

Parity: reference apex/parallel/LARC.py:5-107: wraps any optimizer; per
param computes ``adaptive_lr = trust_coefficient * ||p|| / (||g|| +
weight_decay * ||p|| + eps)``; in ``clip`` mode the effective lr is
``min(adaptive_lr / lr, 1)``; grads are rescaled before the wrapped
optimizer's step.
"""

import jax
import jax.numpy as jnp


class LARC(object):
    def __init__(self, optimizer, trust_coefficient=0.02, clip=True, eps=1e-8,
                 weight_decay=0.0):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        self.weight_decay = weight_decay

    @property
    def lr(self):
        return self.optim.lr

    def init(self, params):
        return self.optim.init(params)

    def _rescale(self, grads, params, lr):
        def scale_one(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive_lr = self.trust_coefficient * p_norm / (
                g_norm + self.weight_decay * p_norm + self.eps)
            # Zero-norm params fall back to the plain lr (reference LARC.py:95).
            adaptive_lr = jnp.where((p_norm > 0) & (g_norm > 0), adaptive_lr, lr)
            if self.clip:
                ratio = jnp.minimum(adaptive_lr / lr, 1.0)
            else:
                ratio = adaptive_lr / lr
            g32 = g32 + self.weight_decay * p32
            return (g32 * ratio).astype(g.dtype)

        return jax.tree_util.tree_map(scale_one, grads, params)

    def step(self, grads, state, params, *, lr=None, found_inf=None, scale=1.0):
        eff_lr = self.optim.lr if lr is None else lr
        grads = self._rescale(grads, params, eff_lr)
        return self.optim.step(grads, state, params, lr=lr,
                               found_inf=found_inf, scale=scale)
