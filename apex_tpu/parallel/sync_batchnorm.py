"""SyncBatchNorm — cross-replica batch norm over a mesh axis.

Parity: reference apex/parallel/sync_batchnorm.py:9-136 (pure-Python
process-group BN) and optimized_sync_batchnorm*.py (CUDA Welford + per-rank
stat merge ``welford_parallel``, channel-last + fused ReLU + additive ``z``
BN-Add-ReLU).

TPU design: per-replica mean / mean-of-squares are computed locally and
merged with a count-weighted ``lax.psum`` — algebraically identical to the
Welford merge across ranks, robust to different per-rank batch sizes
(reference two_gpu_test_different_batch_size.py). Arrays are channels-last
(NHWC), the TPU-native layout — the reference's ``channel_last=True`` fast
path is the default here. Fused ReLU and additive-z variants are kept.
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


def sync_batch_norm(x, mean, var, weight, bias, eps):
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


def _global_stats(x, axis_name, reduce_axes):
    """Count-weighted cross-replica mean/var (welford_parallel semantics,
    reference csrc/welford.cu + optimized_sync_batchnorm_kernel.py:36-44)."""
    count = jnp.asarray(
        jnp.prod(jnp.asarray([x.shape[a] for a in reduce_axes])), jnp.float32)
    local_sum = jnp.sum(x.astype(jnp.float32), axis=reduce_axes)
    local_sqsum = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=reduce_axes)
    if axis_name is not None:
        total_count = lax.psum(count, axis_name)
        total_sum = lax.psum(local_sum, axis_name)
        total_sqsum = lax.psum(local_sqsum, axis_name)
    else:
        total_count, total_sum, total_sqsum = count, local_sum, local_sqsum
    mean = total_sum / total_count
    var = total_sqsum / total_count - jnp.square(mean)
    return mean, var, total_count


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm that synchronizes statistics across ``axis_name``.

    Mirrors flax.linen.BatchNorm's interface plus the reference's
    ``fuse_relu`` / additive ``z`` options (BN-Add-ReLU,
    reference optimized_sync_batchnorm.py:85).
    """

    use_running_average: Optional[bool] = None
    axis_name: Optional[str] = "dp"
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    fuse_relu: bool = False

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None, z=None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average)
        features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))

        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32), (features,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), (features,))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axis = self.axis_name
            if axis is not None:
                # Only sync when the axis is actually bound (supports single-
                # device eager use like the reference's fallback path).
                try:
                    lax.axis_size(axis)
                except (NameError, Exception):
                    axis = None
            mean, var, total_count = _global_stats(x, axis, reduce_axes)
            if not self.is_initializing():
                # Unbiased running var (reference sync_batchnorm.py:80-87).
                unbiased = var * total_count / jnp.maximum(total_count - 1, 1)
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * unbiased

        weight = (self.param("scale", nn.initializers.ones, (features,), self.param_dtype)
                  if self.use_scale else None)
        bias = (self.param("bias", nn.initializers.zeros, (features,), self.param_dtype)
                if self.use_bias else None)

        y = sync_batch_norm(x.astype(jnp.float32), mean, var, weight, bias, self.epsilon)
        if z is not None:
            y = y + z.astype(jnp.float32)
        if self.fuse_relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(self.dtype or x.dtype)
