"""2-D ``(data, model)`` mesh composition — the substrate under TP x DP.

Why: every production mechanism in this repo — int8/int4 compressed
collectives with error feedback, the overlapped per-bucket step, the
guard, numerics, the supervisor, elastic ZeRO — grew up on a 1-D data
mesh, while the Megatron-style ``apex.transformer`` trees pass their
parity tests in isolation. This module is their composition (ROADMAP
item 4): a GPT-2-shaped transformer expressed with column/row-parallel
shards over the ``model`` axis of a named 2-D mesh (the GSPMD pattern
of arXiv 2004.13336's weight-update sharding scoped to the DP axis),
trained with the SAME ``DistributedDataParallel`` /
``OverlappedDataParallel`` / ZeRO machinery — gradient compression and
EF residuals scoped to the ``data`` axis only, TP activation psums
staying full precision.

Axis-scoping rules (docs/parallelism.md "2-D mesh composition"):

- **TP collectives move activations** (the ``copy_to`` backward psum of
  dx, the ``reduce_from`` forward psum of row-parallel partials) and
  stay fp32/bf16 — quantizing them would inject error into the forward
  value itself, not into a gradient that error feedback can absorb.
- **DP collectives move gradients** and compress: ``axis_name="data"``
  threads through ``psum_compressed*`` so the per-block scale pmax and
  the int8/int4 payload psum reduce over the ``data`` axis only. Each
  ``(data, model)`` coordinate keeps its OWN error-feedback residual
  (split params have per-model-rank grads; replicated params carry
  model-identical grads, so their residuals stay model-identical too —
  the invariant the 2-D ZeRO consolidation verifies).
- **Overlap legality**: per-bucket DP psums must not chain behind one
  another (the ``overlap-serialization`` rule, with
  ``overlap_min_bytes`` set between the TP activation-psum payload and
  the per-bucket gradient payload — the regime where the rule separates
  the inherent backward-chain TP psums from an actual bucket
  serialization bug).

The TP math is the ``tensor_parallel.mappings`` region ops themselves
(``copy_to_tensor_model_parallel_region`` /
``reduce_from_tensor_model_parallel_region`` bound to the ``model``
axis) — same custom-vjp collectives the Megatron layer tree uses, so
the lint targets exercise the real forward/backward pairing, not a
reimplementation.

Everything here runs inside ``jax.shard_map`` over a ``Mesh`` built by
:func:`mesh_2d`; parameters live as FULL host arrays placed with
``NamedSharding`` over :func:`gpt2_pspecs` (split leaves sharded over
``model``, everything replicated over ``data``), and shard_map's
in_specs hand each device its local shard.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.telemetry.trace import span as _span
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region as _copy_to,
    reduce_from_tensor_model_parallel_region as _reduce_from,
)

DATA_AXIS = "data"
MODEL_AXIS = "model"

# leaf-name -> (partition dim of the FULL array, PartitionSpec) —
# column-parallel weights split their output dim, their biases ride
# along; row-parallel weights split their input dim and their bias is
# added AFTER the psum (replicated). Everything else replicates.
_COL_W = frozenset({"wq", "wk", "wv", "wi"})
_COL_B = frozenset({"bq", "bk", "bv", "bi"})
_ROW_W = frozenset({"wo"})


def mesh_2d(data=2, model=None, devices=None):
    """The named 2-D ``(data, model)`` mesh: ``data`` rows of ``model``
    columns over the first ``data * model`` devices (default: all of
    them, ``model = len(devices) // data``)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if model is None:
        if len(devices) % data != 0:
            raise ValueError(
                f"mesh_2d: {len(devices)} devices do not split into "
                f"data={data} rows")
        model = len(devices) // data
    need = data * model
    if len(devices) < need:
        raise ValueError(f"mesh_2d: need {need} devices "
                         f"(data={data} x model={model}), have "
                         f"{len(devices)}")
    return Mesh(np.asarray(devices[:need]).reshape(data, model),
                (DATA_AXIS, MODEL_AXIS))


# ---------------------------------------------------------------------------
# GPT-2 params: a stack of pre-LN transformer blocks, segment-shaped
# ---------------------------------------------------------------------------

def gpt2_init(hidden=64, layers=2, heads=4, vocab=64, max_seq=32, *,
              bias=True, seed=0):
    """FULL (unsharded) GPT-2-style params as a tuple of per-layer
    SEGMENT dicts — the container every step mode consumes: segment 0
    carries the (replicated) embedding tables, the last segment the
    final layer norm and the untied LM head. Leaves are fp32; column
    dims must divide by the mesh's ``model`` size."""
    if hidden % heads:
        raise ValueError(f"hidden {hidden} not divisible by heads {heads}")
    rng = np.random.RandomState(seed)

    def w(*shape, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(shape[0])
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)

    def layer():
        p = {
            "ln1": {"g": jnp.ones((hidden,), jnp.float32),
                    "b": jnp.zeros((hidden,), jnp.float32)},
            "attn": {"wq": w(hidden, hidden), "wk": w(hidden, hidden),
                     "wv": w(hidden, hidden), "wo": w(hidden, hidden)},
            "ln2": {"g": jnp.ones((hidden,), jnp.float32),
                    "b": jnp.zeros((hidden,), jnp.float32)},
            "mlp": {"wi": w(hidden, 4 * hidden),
                    "wo": w(4 * hidden, hidden)},
        }
        if bias:
            for name, width in (("bq", hidden), ("bk", hidden),
                                ("bv", hidden), ("bo", hidden)):
                p["attn"][name] = jnp.zeros((width,), jnp.float32)
            p["mlp"]["bi"] = jnp.zeros((4 * hidden,), jnp.float32)
            p["mlp"]["bo"] = jnp.zeros((hidden,), jnp.float32)
        return p

    segments = []
    for i in range(layers):
        seg = {"layer": layer()}
        if i == 0:
            seg["embed"] = {"wte": w(vocab, hidden, scale=0.02),
                            "wpe": w(max_seq, hidden, scale=0.02)}
        if i == layers - 1:
            seg["ln_f"] = {"g": jnp.ones((hidden,), jnp.float32),
                           "b": jnp.zeros((hidden,), jnp.float32)}
            seg["head"] = {"w": w(hidden, vocab)}
        segments.append(seg)
    return tuple(segments)


def _leaf_name(path):
    return str(getattr(path[-1], "key", path[-1]))


def gpt2_partition_dims(seg_params):
    """Pytree (matching ``seg_params``) of the dim each leaf splits
    over the ``model`` axis — ``None`` for replicated leaves. The shard
    table the 2-D ZeRO consolidation
    (:func:`~apex_tpu.contrib.optimizers.distributed_fused_adam.
    consolidate_zero_state_2d`) re-partitions along."""

    def dim(path, leaf):
        name = _leaf_name(path)
        if name in _COL_W:
            return 1
        if name in _COL_B:
            return 0
        if name in _ROW_W:
            return 0
        return None

    return jax.tree_util.tree_map_with_path(dim, seg_params)


def gpt2_pspecs(seg_params):
    """Pytree of ``PartitionSpec`` placing every leaf on the 2-D mesh:
    split leaves shard their partition dim over ``model``; everything
    is replicated over ``data`` (gradients sync there instead)."""

    def spec(path, leaf):
        name = _leaf_name(path)
        if name in _COL_W:
            return P(None, MODEL_AXIS)
        if name in _COL_B:
            return P(MODEL_AXIS)
        if name in _ROW_W:
            # NO trailing None: jit normalizes P("model", None) to
            # P("model") on outputs, and the signature mismatch would
            # cost a second compile on the first carry feedback
            return P(MODEL_AXIS)
        return P()

    return jax.tree_util.tree_map_with_path(spec, seg_params)


def local_template(seg_params, tp):
    """Zeros shaped like each leaf's LOCAL (per-model-rank) shard — what
    ``init_residual`` needs to size the DP error-feedback state on the
    2-D mesh."""
    dims = gpt2_partition_dims(seg_params)

    def shrink(leaf, dim):
        if dim is None:
            return jnp.zeros(leaf.shape, leaf.dtype)
        if leaf.shape[dim] % tp:
            raise ValueError(
                f"leaf dim {dim} of shape {leaf.shape} does not split "
                f"{tp} ways over '{MODEL_AXIS}'")
        shape = list(leaf.shape)
        shape[dim] //= tp
        return jnp.zeros(tuple(shape), leaf.dtype)

    return jax.tree_util.tree_map(shrink, seg_params, dims)


# ---------------------------------------------------------------------------
# the forward math (runs on LOCAL shards inside shard_map)
# ---------------------------------------------------------------------------

def _ln(p, x, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * p["g"] + p["b"]


def _attn(p, x, head_dim, fused=False):
    """Column-parallel QKV (local heads) -> causal attention ->
    row-parallel output projection joined by ONE TP psum.  With
    ``fused`` the projection+psum runs as the fused
    computation-collective kernel (``kernels/fused_cc.py``): the GEMM
    is tiled and each tile's psum fires as it completes, so the full
    fp32 partial never materializes — same wire bytes, same grads."""
    xp = _copy_to(x, MODEL_AXIS)       # identity fwd / psum(dx) bwd
    q = xp @ p["wq"] + p.get("bq", 0.0)
    k = xp @ p["wk"] + p.get("bk", 0.0)
    v = xp @ p["wv"] + p.get("bv", 0.0)
    b, s, local = q.shape
    nh = local // head_dim
    q = q.reshape(b, s, nh, head_dim)
    k = k.reshape(b, s, nh, head_dim)
    v = v.reshape(b, s, nh, head_dim)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
    causal = np.tril(np.ones((s, s), np.bool_))
    scores = jnp.where(causal, scores, -1e9)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1),
                     v).reshape(b, s, local)
    if fused:
        from apex_tpu.kernels import fused_cc
        out = fused_cc.matmul_reduce_from(ctx, p["wo"], MODEL_AXIS)
    else:
        partial = ctx @ p["wo"]        # [.., h/tp] @ [h/tp, h]
        out = _reduce_from(partial, MODEL_AXIS)  # psum fwd / id bwd
    return out + p.get("bo", 0.0)


def _mlp(p, x, fused=False):
    xp = _copy_to(x, MODEL_AXIS)
    h = jax.nn.gelu(xp @ p["wi"] + p.get("bi", 0.0))
    if fused:
        from apex_tpu.kernels import fused_cc
        out = fused_cc.matmul_reduce_from(h, p["wo"], MODEL_AXIS)
    else:
        out = _reduce_from(h @ p["wo"], MODEL_AXIS)
    return out + p.get("bo", 0.0)


def _block(p, x, head_dim, fused=False):
    x = x + _attn(p["attn"], _ln(p["ln1"], x), head_dim, fused=fused)
    x = x + _mlp(p["mlp"], _ln(p["ln2"], x), fused=fused)
    return x


def _xent(logits, labels):
    ls = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(ls, labels[..., None], axis=-1)
    return -jnp.mean(picked)


def gpt2_segments(labels, layers, head_dim, *, poison=None,
                  fused=False):
    """The per-layer segment chain ``segments[k](params_k, carry) ->
    carry`` for :class:`~apex_tpu.parallel.overlap.
    OverlappedDataParallel`: segment 0 embeds the token batch, the last
    segment closes over ``labels`` and returns the scalar loss.
    ``poison`` (an additive scalar, e.g. ``faults.inject_nan`` output)
    enters at the embedding output so a NaN reaches every gradient."""

    def seg0(p, tokens):
        emb = p["embed"]
        x = emb["wte"][tokens] + emb["wpe"][:tokens.shape[1]]
        if poison is not None:
            x = x + poison
        return _block(p["layer"], x, head_dim, fused=fused)

    def seg_mid(p, x):
        return _block(p["layer"], x, head_dim, fused=fused)

    def seg_last(p, x):
        if "layer" in p:
            x = _block(p["layer"], x, head_dim, fused=fused)
        x = _ln(p["ln_f"], x)
        return _xent(x @ p["head"]["w"], labels)

    if layers == 1:
        # segment 0 both embeds and closes the loss
        def only(p, tokens):
            x = seg0({"embed": p["embed"], "layer": p["layer"]}, tokens)
            x = _ln(p["ln_f"], x)
            return _xent(x @ p["head"]["w"], labels)

        return [only]
    return ([seg0] + [seg_mid] * (layers - 2) + [seg_last])


def gpt2_loss(seg_params, tokens, labels, head_dim, *, poison=None,
              fused=False):
    """The whole-model loss (the un-segmented view the baseline step
    differentiates): run the segment chain sequentially."""
    segs = gpt2_segments(labels, len(seg_params), head_dim,
                         poison=poison, fused=fused)
    carry = tokens
    for fn, p in zip(segs, seg_params):
        carry = fn(p, carry)
    return carry


# ---------------------------------------------------------------------------
# step builders (targets / bench / tests share these)
# ---------------------------------------------------------------------------

def _sgd(sp, grads, lr):
    return tuple(
        jax.tree_util.tree_map(lambda w, g: w - lr * g, pk, gk)
        for pk, gk in zip(sp, grads))


def _norm_spec(spec, mesh):
    """Drop mesh axes of size 1 from a placement spec: jit normalizes
    them away on OUTPUT shardings, so placing inputs with the full spec
    would make the first carry feedback a second compiled signature on
    a degenerate (e.g. 1x1) mesh."""
    parts = [None if (p in mesh.shape and mesh.shape[p] == 1) else p
             for p in spec]
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def place_state(mesh, seg_params, *extra):
    """Commit params to their ``NamedSharding`` placement (split leaves
    over ``model``) and every extra carry tree to the replicated
    sharding — so the first call and the steady state share ONE
    compiled signature (compile_count == 1)."""
    pspecs = jax.tree_util.tree_map(lambda s: _norm_spec(s, mesh),
                                    gpt2_pspecs(seg_params))
    # device_put of an already-committed array can ALIAS its buffer on
    # the overlapping device; a later donation would then delete the
    # caller's original — copy first so every build owns its state
    fresh = jax.tree_util.tree_map(jnp.copy, seg_params)
    placed = jax.device_put(
        fresh,
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), pspecs))
    rep = NamedSharding(mesh, P())
    return (placed,) + tuple(
        jax.device_put(jax.tree_util.tree_map(jnp.copy, t), rep)
        for t in extra)


def build_train_step(mesh, seg_params, *, hidden, heads,
                     mode="overlapped", compress="int8", lr=0.05,
                     fold_average=True, message_size=10000000,
                     guard_nan_step=None, donate=True, fused=False):
    """One jitted 2-D train step.

    ``mode="baseline"``: full backward, then the bucketed DP sync
    (exactly the 1-D ``ddp_compressed`` shape, on the 2-D mesh) —
    ``step(sp, res, tokens, labels) -> (sp, res, loss)``.

    ``mode="overlapped"``: segmented backward with per-bucket DP psums
    emitted mid-backward (``parallel/overlap.py``), interleaving with
    the remaining segments' TP psums — same signature.

    ``mode="guarded"``: the overlapped step under
    ``resilience.guarded_update`` with the non-finite flag OR'd over
    BOTH axes (every ``(data, model)`` coordinate must agree to skip) —
    ``step(sp, res, gst, step_idx, tokens, labels) -> (sp, res, gst,
    loss)``; ``guard_nan_step`` arms ``faults.inject_nan`` at the
    embedding output.

    ``fused=True`` routes the TP row-parallel projections through
    ``kernels/fused_cc.matmul_reduce_from`` (tiled GEMM+psum, no fp32
    partial in HBM) — identical wire bytes and gradients, gated by the
    ``fused_cc`` kernel registry entry.

    Returns ``(jitted_step, state)`` where ``state`` is the placed
    carry tuple (params, residual[, guard state]).
    """
    from apex_tpu import resilience
    from apex_tpu.parallel import compression
    from apex_tpu.parallel.distributed import DistributedDataParallel
    from apex_tpu.parallel.overlap import OverlappedDataParallel
    from apex_tpu.resilience import faults

    head_dim = hidden // heads
    layers = len(seg_params)
    tp = mesh.shape[MODEL_AXIS]
    local = local_template(seg_params, tp)
    stateful = compression.needs_residual(compress)
    pspecs = gpt2_pspecs(seg_params)

    if mode == "baseline":
        ddp = DistributedDataParallel(axis_name=DATA_AXIS,
                                      compress=compress,
                                      message_size=message_size)
        residual = (ddp.init_residual(local) if stateful
                    else jnp.zeros(()))

        def fn(sp, res, tokens, labels):
            # phase spans open at trace time (once per compile — the
            # per-step accounting for a compiled step) and join the
            # ambient TraceContext, so the supervisor's train/step
            # trace shows fwd_bwd -> sync -> optimizer as children
            with _span("train/fwd_bwd"):
                loss, grads = jax.value_and_grad(
                    lambda q: gpt2_loss(q, tokens, labels, head_dim,
                                        fused=fused))(tuple(sp))
            if stateful:
                grads, res = ddp.sync(grads, res)
            else:
                grads = ddp.sync(grads)
            with _span("train/optimizer"):
                new_sp = _sgd(sp, grads, lr)
            return new_sp, res, loss

    elif mode in ("overlapped", "guarded"):
        odp = OverlappedDataParallel(axis_name=DATA_AXIS,
                                     compress=compress,
                                     fold_average=fold_average,
                                     message_size=message_size,
                                     guard_flag=(mode == "guarded"))
        residual = (odp.init_residual(local) if stateful
                    else jnp.zeros(()))

        if mode == "overlapped":
            def fn(sp, res, tokens, labels):
                segs = gpt2_segments(labels, layers, head_dim,
                                     fused=fused)
                # the overlap module's per-segment/bucket spans open
                # inside this one, so they parent under train/fwd_bwd
                # in the step's trace
                with _span("train/fwd_bwd"):
                    if stateful:
                        loss, synced, res = odp.value_and_sync(
                            segs, list(sp), tokens, residual=res)
                    else:
                        loss, synced = odp.value_and_sync(
                            segs, list(sp), tokens)
                with _span("train/optimizer"):
                    new_sp = _sgd(sp, synced, lr)
                return new_sp, res, loss
        else:
            def fn(sp, res, gst, step_idx, tokens, labels):
                poison = faults.inject_nan(
                    jnp.zeros((), jnp.float32), step_idx,
                    nan_step=guard_nan_step)
                segs = gpt2_segments(labels, layers, head_dim,
                                     poison=poison, fused=fused)
                with _span("train/fwd_bwd"):
                    loss, synced, new_res, flag = odp.value_and_sync(
                        segs, list(sp), tokens, residual=res)

                def commit(g, st):
                    prev_sp, _ = st
                    return (_sgd(prev_sp, g, lr), new_res)

                with _span("train/optimizer"):
                    (sp, res), gst = resilience.guarded_update(
                        synced, commit, (tuple(sp), res), gst,
                        axis_name=(DATA_AXIS, MODEL_AXIS), flag=flag)
                return sp, res, gst, loss
    else:
        raise ValueError(f"unknown mode {mode!r}")

    rspec = jax.tree_util.tree_map(lambda _: P(), residual)
    if mode == "guarded":
        in_specs = (pspecs, rspec, P(), P(), P(DATA_AXIS), P(DATA_AXIS))
        out_specs = (pspecs, rspec, P(), P())
        donate_argnums = (0, 1, 2) if donate else ()
        state = place_state(mesh, seg_params, residual,
                            resilience.init_guard_state())
    else:
        in_specs = (pspecs, rspec, P(DATA_AXIS), P(DATA_AXIS))
        out_specs = (pspecs, rspec, P())
        donate_argnums = (0, 1) if donate else ()
        state = place_state(mesh, seg_params, residual)

    step = jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False),
        donate_argnums=donate_argnums)
    return step, state


def make_batch(mesh, *, batch_per_replica=2, seq=16, vocab=64, seed=1):
    """A token/label batch sharded over the ``data`` axis (replicated
    over ``model`` — every model rank sees the same rows)."""
    rng = np.random.RandomState(seed)
    rows = batch_per_replica * mesh.shape[DATA_AXIS]
    tokens = jnp.asarray(rng.randint(0, vocab, (rows, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, vocab, (rows, seq)), jnp.int32)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    return jax.device_put((tokens, labels), sharding)
