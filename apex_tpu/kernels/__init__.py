"""apex_tpu.kernels — the Pallas fused-kernel layer (csrc parity).

One registry (:mod:`apex_tpu.kernels.registry`: ``APEX_TPU_KERNELS``
master switch, per-kernel env overrides, jnp oracle fallback always
available, interpreter mode for CPU tests) gating four kernel families
behind their existing Python entry points:

- :mod:`apex_tpu.kernels.norm` — RMSNorm/LayerNorm fwd + bwd-dx
  (entry: ``apex_tpu.normalization`` via ``apex_tpu.ops.layer_norm``)
- :mod:`apex_tpu.kernels.softmax` — scaled-masked / upper-triangular
  softmax fwd + fused bwd (entry:
  ``apex_tpu.transformer.functional.fused_softmax``)
- :mod:`apex_tpu.kernels.optim` — fused multi-tensor Adam/LAMB updates
  over the bucket-domain ZeRO state (entry: the
  ``apex_tpu.contrib.optimizers`` ZeRO classes)
- :mod:`apex_tpu.kernels.quant4` — int4 dual-quantization pack/unpack
  (entry: ``apex_tpu.parallel.compression`` ``compress="int4"``)

See docs/kernels.md for env vars, parity bounds, and wire formats.
"""

from apex_tpu.kernels import norm, optim, quant4, softmax  # noqa: F401
from apex_tpu.kernels.registry import (  # noqa: F401
    KernelRegistry,
    PallasGate,
    choose_block,
    get_kernel_registry,
    kernel_gate,
)
