"""Fused scale + mask + softmax Pallas kernels (forward + backward).

Parity: reference csrc/megatron_fused_kernels —
``scaled_masked_softmax_cuda``, ``scaled_upper_triang_masked_softmax_cuda``
and ``scaled_softmax_cuda``, each a fused fwd kernel plus a bwd kernel
computing ``dx = scale * y * (dy - sum(dy * y))`` from the stashed
probabilities. The jnp entry points in
:mod:`apex_tpu.transformer.functional.fused_softmax` stay the oracle
(and the ``APEX_TPU_KERNELS=0`` path, bit-identical to today including
autodiff gradients); when the ``softmax`` gate is enabled they dispatch
to the ``custom_vjp`` wrappers below, whose backward runs the one-pass
fused formula instead of re-deriving the chain through exp/sum.

Kernel design: scores flatten to ``[rows, sk]`` and grid over row
blocks with the full key dim resident in VMEM. The forward mirrors the
oracle's fp32 operation order exactly (scale, mask to -10000, subtract
row max, exp, re-mask, normalize), so interpret-mode forward parity is
bit-exact; the backward's fused formula is algebraically equal to the
autodiff chain but associates differently — gradients match within
~1e-6 relative in fp32 (the documented bound; see docs/kernels.md).
The causal variant computes its upper-triangular mask *in-kernel* from
the row/key iota (no [sq, sk] mask tensor is ever materialized — the
point of the fused kernel).

Masks follow the reference convention: 1/True where masked OUT.
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.kernels.registry import get_kernel_registry, kernel_gate

GATE = kernel_gate("softmax", default=True)

_MASK_VALUE = -10000.0


def _row_block(n_rows: int, sk: int) -> int:
    budget = 4 * 1024 * 1024
    rows = max(8, budget // max(1, 4 * sk * 4))
    rows = min(rows, 512)
    rows = max(8, (rows // 8) * 8)
    return rows


def usable(scale) -> bool:
    """The kernel path needs a static scale (it is baked into the
    kernel); a traced scale falls back to the oracle."""
    return isinstance(scale, (int, float)) and GATE.enabled()


def record(path: str):
    get_kernel_registry().dispatch("softmax", path)


# ---------------------------------------------------------------------------
# kernel bodies
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, y_ref, *, scale):
    xf = x_ref[...].astype(jnp.float32) * scale
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf)
    y_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _masked_fwd_kernel(x_ref, m_ref, y_ref, *, scale):
    xf = x_ref[...].astype(jnp.float32) * scale
    m = m_ref[...] != 0
    xf = jnp.where(m, _MASK_VALUE, xf)
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf)
    e = jnp.where(m, 0.0, e)
    y_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _causal_fwd_kernel(x_ref, y_ref, *, scale, sq, sk, rb):
    from jax.experimental import pallas as pl

    r0 = pl.program_id(0) * rb
    rows = jax.lax.broadcasted_iota(jnp.int32, (rb, sk), 0) + r0
    i = rows % sq
    j = jax.lax.broadcasted_iota(jnp.int32, (rb, sk), 1)
    causal = j <= i + (sk - sq)
    xf = x_ref[...].astype(jnp.float32) * scale
    xf = jnp.where(causal, xf, _MASK_VALUE)
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf)
    e = jnp.where(causal, e, 0.0)
    y_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _bwd_kernel(y_ref, dy_ref, dx_ref, *, scale):
    y = y_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    t = jnp.sum(dy * y, axis=-1, keepdims=True)
    dx_ref[...] = (scale * y * (dy - t)).astype(dx_ref.dtype)


def _rowwise_call(kernel, x2d, *extra, out_dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, sk = x2d.shape
    rb = _row_block(n, sk)
    spec = pl.BlockSpec((rb, sk), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, rb),),
        in_specs=[spec] * (1 + len(extra)),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((n, sk), out_dtype),
        interpret=GATE.interpret,
    )(x2d, *extra)


def _bwd_rows(y2d, dy2d, scale, out_dtype):
    return _rowwise_call(functools.partial(_bwd_kernel, scale=scale),
                         y2d, dy2d, out_dtype=out_dtype)


# ---------------------------------------------------------------------------
# custom_vjp wrappers (consumed by transformer.functional.fused_softmax)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_softmax(x, scale):
    """No-mask scaled softmax, fused fwd+bwd (any leading dims, softmax
    over the last)."""
    y, _ = _scaled_fwd(x, scale)
    return y


def _scaled_fwd(x, scale):
    x2d = x.reshape(-1, x.shape[-1])
    y = _rowwise_call(functools.partial(_fwd_kernel, scale=scale),
                      x2d, out_dtype=x.dtype)
    y = y.reshape(x.shape)
    return y, y


def _scaled_bwd(scale, y, dy):
    sk = y.shape[-1]
    dx = _bwd_rows(y.reshape(-1, sk), dy.astype(y.dtype).reshape(-1, sk),
                   scale, y.dtype)
    return (dx.reshape(y.shape),)


scaled_softmax.defvjp(_scaled_fwd, _scaled_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def scaled_masked_softmax(x, maskf, scale):
    """Arbitrary-mask scaled softmax; ``maskf`` is an f32 0/1 tensor
    already broadcast to ``x.shape`` (1 where masked OUT — the wrapper
    in fused_softmax does the cast/broadcast)."""
    y, _ = _masked_fwd(x, maskf, scale)
    return y


def _masked_fwd(x, maskf, scale):
    sk = x.shape[-1]
    y = _rowwise_call(
        functools.partial(_masked_fwd_kernel, scale=scale),
        x.reshape(-1, sk), maskf.reshape(-1, sk), out_dtype=x.dtype)
    y = y.reshape(x.shape)
    return y, (y, maskf)


def _masked_bwd(scale, res, dy):
    y, maskf = res
    sk = y.shape[-1]
    dx = _bwd_rows(y.reshape(-1, sk), dy.astype(y.dtype).reshape(-1, sk),
                   scale, y.dtype)
    # masked positions have y == 0, so dx is already 0 there; the mask
    # itself gets a (dead) zero cotangent
    return dx.reshape(y.shape), jnp.zeros_like(maskf)


scaled_masked_softmax.defvjp(_masked_fwd, _masked_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_upper_triang_masked_softmax(x, scale):
    """Causal-masked scaled softmax over ``[b, sq, sk]`` — the mask is
    derived in-kernel from the row index, never materialized."""
    y, _ = _causal_fwd(x, scale)
    return y


def _causal_fwd(x, scale):
    b, sq, sk = x.shape
    x2d = x.reshape(b * sq, sk)
    rb = _row_block(b * sq, sk)
    y = _rowwise_call(
        functools.partial(_causal_fwd_kernel, scale=scale, sq=sq, sk=sk,
                          rb=rb),
        x2d, out_dtype=x.dtype)
    y = y.reshape(x.shape)
    return y, y


def _causal_bwd(scale, y, dy):
    b, sq, sk = y.shape
    dx = _bwd_rows(y.reshape(-1, sk), dy.astype(y.dtype).reshape(-1, sk),
                   scale, y.dtype)
    return (dx.reshape(y.shape),)


scaled_upper_triang_masked_softmax.defvjp(_causal_fwd, _causal_bwd)
