"""int4 dual-quantization kernels: quantize/dequantize + nibble packing.

EQuARX (arXiv 2506.17615) pushes block-scaled quantized collectives to
4 bits with *dual* (two-level) quantization: per-block scales are
themselves quantized against one per-bucket fp32 scale, so the wire
carries half-byte lanes + one byte per 256-lane block + a single fp32
— ~0.53 bytes/element at block 256 vs 1.03 for int8.

Wire format (``compress="int4"`` in parallel/compression.py; spec in
docs/kernels.md):

- values: symmetric int4 in [-7, 7] (−8 excluded, same symmetric-grid
  reasoning as the int8 path's ±127), quantized against the block's
  EFFECTIVE scale below
- level-1 scales: per-block ``sq = clip(round(absmax / gmax * 255),
  1, 255)`` stored uint8 (the floor at 1 keeps all-zero blocks exact
  instead of dividing by 0)
- level-2 scale: one fp32 ``gmax = max(absmax)`` per bucket
- effective block scale: ``sq * gmax / (255 * 7)``
- packing (the genuinely-int4 gather payload): SPLIT-HALF nibbles —
  ``packed[b, j] = (q[b, j] & 0xF) | (q[b, j + B/2] & 0xF) << 4`` —
  chosen over interleaved pairs because both halves are contiguous
  128-lane slices, which the TPU lane layout handles without a
  shuffle.

Replica agreement: the collective paths pmax the fp32 block absmaxes
(exactly like int8), then EVERY replica derives ``(sq, gmax)`` from the
shared absmaxes — a deterministic function — so all replicas quantize
against the same grid and an int32-partial psum is exact.

The jnp formulations are the Pallas kernels' parity oracles (identical
operation order — interpret-mode parity is bit-exact) and the fallback
off TPU. Gate: ``quant4``.
"""

import jax
import jax.numpy as jnp

from apex_tpu.kernels.registry import get_kernel_registry, kernel_gate

GATE = kernel_gate("quant4", default=True)

QMAX4 = 7.0
_SCALE_QMAX = 255.0

# int8 tiles at 32 sublanes; one grid cell covers 32 blocks (the same
# cell the int8 compression kernels use)
_ROWS = 32


def record(path=None):
    gate = GATE
    if path is None:
        path = ("interpret" if gate.interpret else "pallas") \
            if gate.enabled() else "oracle"
    get_kernel_registry().dispatch("quant4", path)


def int4_block_scales(absmax):
    """Two-level scales from (shared) per-block absmaxes:
    ``(sq uint8 [nb, 1], gmax fp32 scalar)``."""
    gmax = jnp.maximum(jnp.max(absmax), 1e-12)
    sq = jnp.clip(jnp.round(absmax / gmax * _SCALE_QMAX), 1.0,
                  _SCALE_QMAX).astype(jnp.uint8)
    return sq, gmax


def effective_scales(sq, gmax):
    """The dequantization grid the wire format implies: ``[nb, 1]``
    fp32."""
    return sq.astype(jnp.float32) * (gmax / (_SCALE_QMAX * QMAX4))


# ---------------------------------------------------------------------------
# jnp oracles
# ---------------------------------------------------------------------------

def _quantize_jnp(x2d, scales):
    return jnp.clip(jnp.round(x2d / scales), -QMAX4, QMAX4) \
        .astype(jnp.int8)


def _dequantize_jnp(q2d, scales):
    return q2d.astype(jnp.float32) * scales


def _pad_even_lanes(q2d):
    """A ragged tail block (lane count not a multiple of the pack
    width) pads ONE zero lane so the split-half nibble layout stays
    well-formed; ``unpack(..., n=)`` drops it on the way back."""
    if q2d.shape[1] % 2:
        q2d = jnp.pad(q2d, ((0, 0), (0, 1)))
    return q2d


def _pack_jnp(q2d):
    q2d = _pad_even_lanes(q2d)
    h = q2d.shape[1] // 2
    lo = q2d[:, :h].astype(jnp.int32) & 0xF
    hi = q2d[:, h:].astype(jnp.int32) & 0xF
    return (lo | (hi << 4)).astype(jnp.uint8)


def _unpack_jnp(p2d, n=None):
    p = p2d.astype(jnp.int32)
    lo = ((p & 0xF) ^ 8) - 8
    hi = (((p >> 4) & 0xF) ^ 8) - 8
    out = jnp.concatenate([lo, hi], axis=1).astype(jnp.int8)
    return out if n is None else out[:, :n]


# ---------------------------------------------------------------------------
# Pallas kernels (same bodies, ref-indexed)
# ---------------------------------------------------------------------------

def _quant_kernel(x_ref, s_ref, q_ref):
    q_ref[...] = jnp.clip(jnp.round(x_ref[...] / s_ref[...]),
                          -QMAX4, QMAX4).astype(jnp.int8)


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[...]


def _pack_kernel(q_ref, p_ref):
    h = q_ref.shape[1] // 2
    lo = q_ref[:, :h].astype(jnp.int32) & 0xF
    hi = q_ref[:, h:].astype(jnp.int32) & 0xF
    p_ref[...] = (lo | (hi << 4)).astype(jnp.uint8)


def _unpack_kernel(p_ref, q_ref):
    p = p_ref[...].astype(jnp.int32)
    lo = ((p & 0xF) ^ 8) - 8
    hi = (((p >> 4) & 0xF) ^ 8) - 8
    q_ref[...] = jnp.concatenate([lo, hi], axis=1).astype(jnp.int8)


def _pad_rows(x2d, rows=_ROWS):
    nb = x2d.shape[0]
    pad = (-nb) % rows
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d, nb


def _cellwise(kernel, out_dtype, out_cols, x2d, *extra):
    """Launch a 32-row-cell kernel over [nb, cols] operands (scales
    pad with ones so padded rows divide by 1)."""
    from jax.experimental import pallas as pl

    x2d, nb = _pad_rows(x2d)
    args = [x2d]
    in_specs = [pl.BlockSpec((_ROWS, x2d.shape[1]), lambda i: (i, 0))]
    for e in extra:
        if e.shape[1] == 1:  # scales column: pad with ones
            e = jnp.concatenate(
                [e, jnp.ones((x2d.shape[0] - nb, 1), e.dtype)])
        else:
            e, _ = _pad_rows(e)
        args.append(e)
        in_specs.append(pl.BlockSpec((_ROWS, e.shape[1]),
                                     lambda i: (i, 0)))
    out = pl.pallas_call(
        kernel,
        grid=(x2d.shape[0] // _ROWS,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((_ROWS, out_cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x2d.shape[0], out_cols),
                                       out_dtype),
        interpret=GATE.interpret,
    )(*args)
    return out[:nb]


# ---------------------------------------------------------------------------
# public (gated) entry points — consumed by parallel/compression.py
# ---------------------------------------------------------------------------

def quantize_int4(x2d, scales):
    """[nb, B] fp32 + effective scales -> int4-valued int8 codes."""
    if GATE.enabled():
        def k(x_ref, s_ref, q_ref):
            _quant_kernel(x_ref, s_ref, q_ref)
        return _cellwise(k, jnp.int8, x2d.shape[1], x2d, scales)
    return _quantize_jnp(x2d, scales)


def dequantize_int4(q2d, scales):
    """int4 codes (or int32 psum partials) + effective scales -> fp32."""
    if GATE.enabled() and q2d.dtype == jnp.int8:
        def k(q_ref, s_ref, o_ref):
            _dequant_kernel(q_ref, s_ref, o_ref)
        return _cellwise(k, jnp.float32, q2d.shape[1], q2d, scales)
    return _dequantize_jnp(q2d, scales)


def pack_int4(q2d):
    """[nb, B] int4 codes -> [nb, ceil(B/2)] uint8 split-half nibbles
    (a ragged odd-B tail pads one zero lane)."""
    if GATE.enabled():
        q2d = _pad_even_lanes(q2d)

        def k(q_ref, p_ref):
            _pack_kernel(q_ref, p_ref)
        return _cellwise(k, jnp.uint8, q2d.shape[1] // 2, q2d)
    return _pack_jnp(q2d)


def unpack_int4(p2d, n=None):
    """[nb, B/2] uint8 nibbles -> [nb, B] int4-valued int8 codes;
    ``n`` truncates a ragged tail's pad lane back off."""
    if GATE.enabled():
        def k(p_ref, q_ref):
            _unpack_kernel(p_ref, q_ref)
        out = _cellwise(k, jnp.int8, p2d.shape[1] * 2, p2d)
        return out if n is None else out[:, :n]
    return _unpack_jnp(p2d, n)
