"""Fused computation-collective kernels (ROADMAP open item 3).

Every hot path used to run compute-then-collective as two XLA ops: the
mesh2d row-parallel matmuls materialized a full fp32 partial before the
TP psum, the speculative engine dequantized int8 KV blocks into HBM
before the k+1-position verify attention, and the int4 collectives
round-tripped the packed payload through HBM on both sides of the ring.
This module fuses each pair, following arXiv 2305.06942 (GEMM +
reduce-scatter / all-gather + GEMM decompositions) and T3 (arXiv
2401.16677: fire the collective as tiles complete, not after the full
product):

- family (a) — ``matmul_reduce_from`` / ``matmul_reduce_scatter`` /
  ``all_gather_matmul``: the GEMM is tiled so each output tile enters
  the collective as it finishes.  ``matmul_reduce_from`` psums column
  tiles of the product (T small psums instead of one big one after the
  whole partial); the scatter/gather forms run the ring explicitly —
  one ``ppermute`` per step interleaved with the chunk GEMMs, so only
  a 1/g-size chunk is ever live instead of the full partial.
- family (b) — ``window_attention`` / ``spec_verify_attention``: one
  flash kernel for the w-position verify window of the speculative
  path (and any multi-token decode chunk).  The int8 form dequantizes
  KV blocks IN REGISTERS (scales applied in VMEM) — the dequantized
  cache tensor never exists in HBM.
- family (c) — ``quantize_pack_int4`` / ``unpack_dequantize_int4``:
  quant4's quantize+pack collapsed into one kernel on the send side
  and unpack+dequant on the receive side, so the int4 code tensor
  never round-trips HBM around the collective.

Every entry point carries a jnp/XLA oracle at IDENTICAL collective
semantics: the fused decomposition moves exactly the bytes the unfused
op moves (T psums of payload/T = one psum of payload under the ring
model; g-1 permutes of payload/g = one reduce-scatter; g-1 permutes of
a shard = one all-gather), records the same trace-time telemetry, and
prices identically under ``analysis/sharding.py``'s static auditor —
which also knows the TPU custom_call target names below so a fused op
in lowered HLO is priced, not dropped.  Gate: ``fused_cc``.
"""

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.kernels import quant4 as _quant4
from apex_tpu.kernels.registry import (
    choose_block,
    get_kernel_registry,
    kernel_gate,
)
from apex_tpu.telemetry.comm import axis_world, record_collective

GATE = kernel_gate("fused_cc", default=True)

NEG_INF = -1e30
DEFAULT_BLOCK_T = 512
# column tiles for the tiled-psum matmul_reduce_from: each tile's psum
# fires as the tile finishes (the T3 track-and-trigger schedule)
DEFAULT_TILES = 4

# The custom_call target each fused family lowers to on TPU, mapped to
# the collective KIND it subsumes.  analysis/sharding.py prices a
# custom_call with one of these targets exactly like the named
# collective (payload from the ``apex_payload_bytes`` frontend
# attribute, group size from ``apex_group_size`` / replica_groups) —
# the static comm-bytes gate survives fusion.
FUSED_CC_CUSTOM_CALL_TARGETS = {
    "apex_fused_cc_matmul_all_reduce": "all_reduce",
    "apex_fused_cc_matmul_reduce_scatter": "reduce_scatter",
    "apex_fused_cc_all_gather_matmul": "all_gather",
    "apex_fused_cc_quant4_all_gather": "all_gather",
}


def record(path=None):
    gate = GATE
    if path is None:
        path = ("interpret" if gate.interpret else "pallas") \
            if gate.enabled() else "oracle"
    get_kernel_registry().dispatch("fused_cc", path)


# ---------------------------------------------------------------------------
# family (a): matmul <-> collective fusion (mesh2d TP blocks)
# ---------------------------------------------------------------------------

def _mm_kernel(x_ref, w_ref, o_ref):
    o_ref[...] = jnp.dot(x_ref[...], w_ref[...],
                         preferred_element_type=jnp.float32)


def _matmul(x, w):
    """``x @ w`` with the trailing contraction run as a row-tiled
    Pallas GEMM when the gate is on (the compute half of every fused
    form); jnp fallback otherwise."""
    if not GATE.enabled():
        return x @ w
    from jax.experimental import pallas as pl

    lead, k = x.shape[:-1], x.shape[-1]
    n = w.shape[-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    pad = (-m) % 8
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    mp = x2.shape[0]
    rb = next(b for b in (128, 64, 32, 16, 8) if mp % b == 0)
    out = pl.pallas_call(
        _mm_kernel,
        grid=(mp // rb,),
        in_specs=[pl.BlockSpec((rb, k), lambda i: (i, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((rb, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, n), jnp.float32),
        interpret=GATE.interpret,
    )(x2, w)
    return out[:m].reshape(*lead, n).astype(
        jnp.result_type(x.dtype, w.dtype))


def _col_tiles(n, want=DEFAULT_TILES):
    """Largest tile count <= ``want`` dividing the output width."""
    for t in range(min(want, n), 0, -1):
        if n % t == 0:
            return t
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_reduce_from(x, w, axis_name, tiles=DEFAULT_TILES):
    """Row-parallel projection joined by the TP reduction:
    semantically ``reduce_from(x @ w)`` — psum forward, identity
    backward (the mesh2d ``_reduce_from(partial @ wo)`` composition).

    Fused path: the GEMM runs in ``tiles`` column tiles and each
    tile's psum fires as the tile completes, so the full fp32 partial
    product never materializes in HBM — only a 1/T-width tile is live
    at a time.  Oracle and fused path move identical wire bytes
    (T psums of payload/T == one psum of payload under the ring
    model)."""
    return _matmul_reduce_from_fwd(x, w, axis_name, tiles)[0]


def _matmul_reduce_from_fwd(x, w, axis_name, tiles):
    n = w.shape[-1]
    if not GATE.enabled():
        record("oracle")
        partial = x @ w
        record_collective("psum", elements=partial.size,
                          dtype=partial.dtype, axis_name=axis_name)
        return lax.psum(partial, axis_name), (x, w)
    record()
    t = _col_tiles(n, tiles)
    tn = n // t
    outs = []
    for i in range(t):
        tile = _matmul(x, lax.slice_in_dim(w, i * tn, (i + 1) * tn,
                                           axis=-1))
        record_collective("psum", elements=tile.size, dtype=tile.dtype,
                          axis_name=axis_name)
        outs.append(lax.psum(tile, axis_name))
    return jnp.concatenate(outs, axis=-1), (x, w)


def _matmul_reduce_from_bwd(axis_name, tiles, res, dy):
    # reduce_from is identity under transposition; the matmul grads
    # are the plain local products (dw is the rank's own shard grad,
    # dx feeds _copy_to whose backward psums it)
    x, w = res
    dx = (dy @ w.T).astype(x.dtype)
    x2 = x.reshape(-1, x.shape[-1])
    dy2 = dy.reshape(-1, dy.shape[-1])
    dw = (x2.T @ dy2).astype(w.dtype)
    return dx, dw


matmul_reduce_from.defvjp(_matmul_reduce_from_fwd,
                          _matmul_reduce_from_bwd)


def matmul_reduce_scatter(x, w, axis_name):
    """``psum_scatter(x @ w)`` over the leading axis (tiled): each
    rank ends with its 1/g row-slice of the reduced product.

    Fused path: ring reduce-scatter interleaved with the chunk GEMMs —
    at step s each rank computes the chunk the partial sum passing
    through it needs next and adds it, so only an [m/g, n] chunk is
    ever live (never the [m, n] partial).  Wire bytes: g-1 permutes of
    payload/g == one reduce-scatter of payload."""
    m = x.shape[0]
    g = axis_world(axis_name)
    if not GATE.enabled() or g <= 1 or m % g:
        record("oracle")
        partial = x @ w
        record_collective("psum_scatter", elements=partial.size,
                          dtype=partial.dtype, axis_name=axis_name)
        if g <= 1:
            return partial
        return lax.psum_scatter(partial, axis_name,
                                scatter_dimension=0, tiled=True)
    record()
    chunk = m // g
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % g) for i in range(g)]

    def gemm_chunk(c):
        rows = lax.dynamic_slice_in_dim(x, c * chunk, chunk, axis=0)
        return _matmul(rows, w)

    acc = None
    for s in range(g):
        c = (r - 1 - s) % g
        if acc is None:
            acc = gemm_chunk(c)
        else:
            record_collective("ppermute", elements=acc.size,
                              dtype=acc.dtype, axis_name=axis_name)
            acc = lax.ppermute(acc, axis_name, perm) + gemm_chunk(c)
    return acc


def all_gather_matmul(x_shard, w, axis_name):
    """``all_gather(x_shard, tiled=True) @ w``: column-parallel input
    assembled on the fly.

    Fused path: each rank GEMMs its resident chunk into the right
    row-slice of the output while the ring permute ships the next
    chunk in — the gathered [m, k] activation never materializes.
    Wire bytes: g-1 permutes of the shard == one all-gather."""
    ms, k = x_shard.shape
    g = axis_world(axis_name)
    if not GATE.enabled() or g <= 1:
        record("oracle")
        record_collective("all_gather", elements=x_shard.size,
                          dtype=x_shard.dtype, axis_name=axis_name)
        full = x_shard if g <= 1 else lax.all_gather(
            x_shard, axis_name, axis=0, tiled=True)
        return full @ w
    record()
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % g) for i in range(g)]
    n = w.shape[-1]
    out = jnp.zeros((g * ms, n),
                    jnp.result_type(x_shard.dtype, w.dtype))
    cur = x_shard
    for s in range(g):
        src = (r - s) % g
        out = lax.dynamic_update_slice_in_dim(out, _matmul(cur, w),
                                              src * ms, axis=0)
        if s < g - 1:
            record_collective("ppermute", elements=cur.size,
                              dtype=cur.dtype, axis_name=axis_name)
            cur = lax.ppermute(cur, axis_name, perm)
    return out


# ---------------------------------------------------------------------------
# family (b): flash verify-window attention (speculative engine)
# ---------------------------------------------------------------------------

# trace-time serving knob: ServeConfig.fused_verify enters here so the
# engine can opt its AOT-traced step functions out without touching
# the process-wide gate
_VERIFY_ENABLED = True


@contextlib.contextmanager
def verify_scope(enabled):
    global _VERIFY_ENABLED
    old = _VERIFY_ENABLED
    _VERIFY_ENABLED = bool(enabled)
    try:
        yield
    finally:
        _VERIFY_ENABLED = old


def use_window(cache_len, block_t=DEFAULT_BLOCK_T):
    """True when the window kernel would actually run (gate on, the
    serving scope hasn't opted out, and a tile divides the cache
    buffer)."""
    return GATE.enabled() and _VERIFY_ENABLED \
        and choose_block(cache_len, block_t) is not None


def window_attention_reference(qg, kt, vt, start, sm_scale,
                               window=None, softcap=None):
    """Einsum oracle: qg [w, b, g, rep, d] queries at absolute
    positions ``start + i``, kt/vt [T, b, g, d] cache buffers (window
    rows already written) -> ctx [w, b, g, rep, d] fp32.  Mask: causal
    at each query's own position, plus the optional sliding window."""
    s = jnp.einsum("sbgrd,tbgd->bgrst", qg.astype(jnp.float32),
                   kt.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if softcap is not None:
        cap = jnp.float32(softcap)
        s = cap * jnp.tanh(s / cap)
    w = qg.shape[0]
    ipos = start + jnp.arange(w)[:, None]
    jpos = jnp.arange(kt.shape[0])[None, :]
    masked = jpos > ipos
    if window is not None:
        masked = masked | (ipos - jpos >= window)
    s = jnp.where(masked[None, None, None], NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bgrst,tbgd->sbgrd", p, vt.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _window_kernel(start_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                   m_ref, l_ref, *, sm_scale, softcap, window, block_t,
                   num_t, w, rep):
    """One (batch, group, cache-tile) cell: all w*rep query rows of
    the verify window share the streamed tile, online softmax across
    the tile axis, per-row causal mask at each window position."""
    from jax.experimental import pallas as pl

    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    start = start_ref[0]
    live = j * block_t <= start + w - 1

    @pl.when(live)
    def _step():
        d = q_ref.shape[-1]
        q = q_ref[...].reshape(w * rep, d).astype(jnp.float32) \
            * sm_scale
        k = k_ref[:, 0, 0, :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if softcap is not None:
            cap = jnp.float32(softcap)
            s = cap * jnp.tanh(s / cap)
        t_ids = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // rep
        masked = t_ids > qpos
        if window is not None:
            masked = masked | (qpos - t_ids >= window)
        s = jnp.where(masked, NEG_INF, s)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1)[:, None])
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1)[:, None]
        m_ref[...] = m_new
        vv = v_ref[:, 0, 0, :].astype(jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, vv, preferred_element_type=jnp.float32)

    @pl.when(j == num_t - 1)
    def _finish():
        d = q_ref.shape[-1]
        o_ref[...] = (acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)) \
            .reshape(w, 1, 1, rep, d)


def _window_pallas(qg, kt, vt, start, sm_scale, softcap, window,
                   block_t):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    w, b, g, rep, d = qg.shape
    T = kt.shape[0]
    num_t = T // block_t
    kernel = functools.partial(
        _window_kernel, sm_scale=sm_scale, softcap=softcap,
        window=window, block_t=block_t, num_t=num_t, w=w, rep=rep)

    def kv_index(bi, gi, j, start_ref):
        # clamp into the live tile range: a repeated block index skips
        # the DMA for the dead tail beyond the verify window
        last = jnp.maximum(start_ref[0] + w - 1, 0) // block_t
        return (jnp.minimum(j, last), bi, gi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, g, num_t),
        in_specs=[
            pl.BlockSpec((w, 1, 1, rep, d),
                         lambda bi, gi, j, start_ref: (0, bi, gi, 0, 0)),
            pl.BlockSpec((block_t, 1, 1, d), kv_index),
            pl.BlockSpec((block_t, 1, 1, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (w, 1, 1, rep, d),
            lambda bi, gi, j, start_ref: (0, bi, gi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((w * rep, d), jnp.float32),  # acc
            pltpu.VMEM((w * rep, 1), jnp.float32),  # running max
            pltpu.VMEM((w * rep, 1), jnp.float32),  # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((w, b, g, rep, d), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=GATE.interpret,
    )(jnp.asarray(start, jnp.int32).reshape(1), qg, kt, vt)


def window_attention(qg, kt, vt, start, sm_scale, window=None,
                     softcap=None, block_t=DEFAULT_BLOCK_T):
    """Flash attention for a w-position decode window (the speculative
    verify chunk; any multi-token continuation chunk).

    qg:     [w, b, g, rep, d] grouped queries at positions start..
            start+w-1.
    kt, vt: [T, b, g, d] cache buffers with the window rows written.
    start:  [] int32 — absolute position of the first window query.
    Returns ctx [w, b, g, rep, d] fp32.  Falls back to the einsum
    oracle when the gate is off or no tile divides the buffer."""
    T = kt.shape[0]
    if not use_window(T, block_t):
        record("oracle")
        return window_attention_reference(qg, kt, vt, start, sm_scale,
                                          window, softcap)
    record()
    return _window_pallas(qg, kt, vt, start, sm_scale, softcap, window,
                          choose_block(T, block_t))


def spec_verify_reference(q, kq, ks, vq, vs, start, sm_scale):
    """Unfused oracle for the int8-KV verify: dequantize the blockwise
    cache into a full fp32 tensor (exactly
    ``KVCacheSpec.materialize_rows``' semantics), then run the window
    attention.  q [w, g, rep, d]; kq/vq [T, nb, B] int8; ks/vs
    [T, nb, 1] fp32 scales."""
    from apex_tpu.parallel import compression

    T = kq.shape[0]
    w, g, rep, d = q.shape
    k = compression.dequantize_rows_blockwise(kq, ks, n=g * d) \
        .reshape(T, g, d)
    v = compression.dequantize_rows_blockwise(vq, vs, n=g * d) \
        .reshape(T, g, d)
    return window_attention_reference(
        q[:, None], k[:, None], v[:, None], start, sm_scale)[:, 0]


def _verify_kernel(start_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref,
                   o_ref, acc_ref, m_ref, l_ref, *, sm_scale, block_t,
                   num_t, w, rep, d):
    """int8-KV verify cell: the tile's quantized blocks are widened
    and scaled IN VMEM (``kq * ks`` per block), so the dequantized
    cache never exists in HBM — the fused alternative to
    ``materialize_rows`` + einsum."""
    from jax.experimental import pallas as pl

    gi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    start = start_ref[0]
    live = j * block_t <= start + w - 1

    @pl.when(live)
    def _step():
        q = q_ref[:, 0].reshape(w * rep, d).astype(jnp.float32) \
            * sm_scale
        # in-register dequant: [block_t, nb, B] * [block_t, nb, 1]
        kt = (kq_ref[...].astype(jnp.float32) * ks_ref[...]) \
            .reshape(block_t, -1)
        k = jax.lax.dynamic_slice_in_dim(kt, gi * d, d, axis=1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        t_ids = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // rep
        s = jnp.where(t_ids > qpos, NEG_INF, s)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1)[:, None])
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1)[:, None]
        m_ref[...] = m_new
        vt = (vq_ref[...].astype(jnp.float32) * vs_ref[...]) \
            .reshape(block_t, -1)
        vv = jax.lax.dynamic_slice_in_dim(vt, gi * d, d, axis=1)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, vv, preferred_element_type=jnp.float32)

    @pl.when(j == num_t - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)) \
            .reshape(w, 1, rep, d)


def spec_verify_attention(q, kq, ks, vq, vs, start, sm_scale,
                          block_t=DEFAULT_BLOCK_T):
    """Fused verify attention over the int8 blockwise KV cache of ONE
    serving slot (``vmap`` over slots for a batch): q [w, g, rep, d]
    at positions start..start+w-1, kq/vq [T, nb, B] int8 codes, ks/vs
    [T, nb, 1] fp32 block scales, with g*d <= nb*B (trailing lanes are
    quantization padding).  Returns ctx [w, g, rep, d] fp32."""
    T = kq.shape[0]
    w, g, rep, d = q.shape
    if not use_window(T, block_t):
        record("oracle")
        return spec_verify_reference(q, kq, ks, vq, vs, start, sm_scale)
    record()
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block = choose_block(T, block_t)
    num_t = T // block
    nb, B = kq.shape[1], kq.shape[2]
    kernel = functools.partial(
        _verify_kernel, sm_scale=sm_scale, block_t=block, num_t=num_t,
        w=w, rep=rep, d=d)

    def kv_index(gi, j, start_ref):
        last = jnp.maximum(start_ref[0] + w - 1, 0) // block
        return (jnp.minimum(j, last), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g, num_t),
        in_specs=[
            pl.BlockSpec((w, 1, rep, d),
                         lambda gi, j, start_ref: (0, gi, 0, 0)),
            pl.BlockSpec((block, nb, B), kv_index),
            pl.BlockSpec((block, nb, 1), kv_index),
            pl.BlockSpec((block, nb, B), kv_index),
            pl.BlockSpec((block, nb, 1), kv_index),
        ],
        out_specs=pl.BlockSpec((w, 1, rep, d),
                               lambda gi, j, start_ref: (0, gi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((w * rep, d), jnp.float32),
            pltpu.VMEM((w * rep, 1), jnp.float32),
            pltpu.VMEM((w * rep, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((w, g, rep, d), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=GATE.interpret,
    )(jnp.asarray(start, jnp.int32).reshape(1), q, kq, ks, vq, vs)


# ---------------------------------------------------------------------------
# family (c): quantize-into-ring int4
# ---------------------------------------------------------------------------

def _cellwise(kernel, out_dtype, out_cols, x2d, *extra):
    """quant4's 32-row-cell launcher, under THIS gate's interpret flag
    (the two gates may be toggled independently in benches)."""
    from jax.experimental import pallas as pl

    x2d, nb = _quant4._pad_rows(x2d)
    args = [x2d]
    in_specs = [pl.BlockSpec((_quant4._ROWS, x2d.shape[1]),
                             lambda i: (i, 0))]
    for e in extra:
        if e.shape[1] == 1:  # scales column: pad with ones
            e = jnp.concatenate(
                [e, jnp.ones((x2d.shape[0] - nb, 1), e.dtype)])
        else:
            e, _ = _quant4._pad_rows(e)
        args.append(e)
        in_specs.append(pl.BlockSpec((_quant4._ROWS, e.shape[1]),
                                     lambda i: (i, 0)))
    out = pl.pallas_call(
        kernel,
        grid=(x2d.shape[0] // _quant4._ROWS,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((_quant4._ROWS, out_cols),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x2d.shape[0], out_cols),
                                       out_dtype),
        interpret=GATE.interpret,
    )(*args)
    return out[:nb]


def _qp_kernel(x_ref, s_ref, p_ref):
    q = jnp.clip(jnp.round(x_ref[...] / s_ref[...]),
                 -_quant4.QMAX4, _quant4.QMAX4).astype(jnp.int32)
    h = q.shape[1] // 2
    p_ref[...] = ((q[:, :h] & 0xF) | ((q[:, h:] & 0xF) << 4)) \
        .astype(jnp.uint8)


def _ud_kernel(p_ref, s_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    lo = ((p & 0xF) ^ 8) - 8
    hi = (((p >> 4) & 0xF) ^ 8) - 8
    o_ref[...] = jnp.concatenate([lo, hi], axis=1) \
        .astype(jnp.float32) * s_ref[...]


def quantize_pack_int4(x2d, scales):
    """Send-side fusion of quant4's quantize + pack: [nb, B] fp32 ->
    [nb, ceil(B/2)] uint8 nibbles in ONE kernel — the int4 code tensor
    never lands in HBM before the collective."""
    if x2d.shape[1] % 2:
        x2d = jnp.pad(x2d, ((0, 0), (0, 1)))
    if GATE.enabled():
        record()
        return _cellwise(_qp_kernel, jnp.uint8, x2d.shape[1] // 2,
                         x2d, scales)
    record("oracle")
    return _quant4._pack_jnp(_quant4._quantize_jnp(x2d, scales))


def unpack_dequantize_int4(p2d, scales, n=None):
    """Receive-side fusion of unpack + dequantize: [nb, B/2] uint8 ->
    [nb, B] fp32 (optionally truncated to ``n`` real lanes) in ONE
    kernel."""
    if GATE.enabled():
        record()
        out = _cellwise(_ud_kernel, jnp.float32, p2d.shape[1] * 2,
                        p2d, scales)
    else:
        record("oracle")
        out = _quant4._dequantize_jnp(_quant4._unpack_jnp(p2d), scales)
    return out[:, :n] if n is not None else out


# ---------------------------------------------------------------------------
# HBM-intermediate accounting (the bench's "eliminated tensors" count)
# ---------------------------------------------------------------------------

def count_jaxpr_avals(closed, predicate):
    """Count equation outputs in a traced jaxpr whose aval satisfies
    ``predicate`` — WITHOUT recursing into ``pallas_call`` bodies
    (kernel-interior values live in VMEM; everything at this level is
    an HBM tensor).  This is how the fused_cc bench proves the fp32
    partial / dequantized-cache / int4-code intermediates are gone:
    the fused trace simply no longer contains an HBM value of that
    shape."""
    def walk(jaxpr):
        total = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                continue
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and getattr(aval, "shape", None) \
                        is not None and predicate(aval):
                    total += 1
            for val in eqn.params.values():
                for sub in _subjaxprs(val):
                    total += walk(sub)
        return total

    def _subjaxprs(val):
        import jax.core as jcore

        if isinstance(val, jcore.ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, jcore.Jaxpr):
            yield val
        elif isinstance(val, (tuple, list)):
            for v in val:
                yield from _subjaxprs(v)

    return walk(closed.jaxpr)


def shape_predicate(shape, dtype):
    """Predicate for :func:`count_jaxpr_avals`: an HBM value of
    exactly this shape and dtype."""
    shape = tuple(shape)
    dt = jnp.dtype(dtype)

    def pred(aval):
        return tuple(aval.shape) == shape and aval.dtype == dt

    return pred


def dtype_predicate(dtype):
    """Predicate matching any HBM value of the dtype (the int4-code
    int8 tensors family (c) eliminates)."""
    dt = jnp.dtype(dtype)

    def pred(aval):
        return aval.dtype == dt and len(aval.shape) > 0

    return pred
