"""Fused LayerNorm / RMSNorm Pallas kernels (forward + backward-dx).

Parity: reference csrc/layer_norm_cuda_kernel.cu — the fused row-stat
kernels behind ``fused_layer_norm_cuda.forward[_affine]`` /
``backward[_affine]`` / ``rms_*``. The public entry points stay in
:mod:`apex_tpu.ops.layer_norm` (custom VJP + shape handling); this
module owns the kernel bodies and their registry gates so the
pallas-vs-oracle decision rides the one ladder in
:mod:`apex_tpu.kernels.registry`.

Kernel design: one kernel per (fwd, bwd-dx) pass, gridded over row
blocks with the full hidden dim resident in VMEM; per-row statistics
are computed in fp32 on the VPU, mirroring the jnp oracle's operation
order exactly — in interpreter mode the kernels are bit-identical to
the oracle (the parity tests assert equality, not closeness). The
backward *recomputes* the row stats from the stashed input instead of
round-tripping them through HBM (stats are VPU-cheap; HBM bandwidth is
the bottleneck). Weight/bias grads are column-sum reductions XLA
already does optimally, so they stay jnp in the VJP.

Gates: ``layernorm`` / ``rmsnorm``, registered ``default=False`` — on
a real chip (BERT-large, hidden 1024) the jnp lowering measured ~14%
faster end-to-end because XLA's own LN fusion matches the kernel's
bandwidth while the custom-call is a fusion barrier. The kernels stay
available for shapes XLA handles poorly (``APEX_TPU_KERNEL_LAYERNORM=1``
/ ``APEX_TPU_KERNEL_RMSNORM=1``, or the legacy ``APEX_TPU_PALLAS_LN=1``
both honor) and are kept correct by the interpret-mode test suite.
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.kernels.registry import kernel_gate

GATE_LN = kernel_gate("layernorm", default=False,
                      legacy_env="APEX_TPU_PALLAS_LN")
GATE_RMS = kernel_gate("rmsnorm", default=False,
                       legacy_env="APEX_TPU_PALLAS_LN")


def _row_block(n_rows: int, hidden: int) -> int:
    # Keep x, y and temps for a block within a few MB of VMEM.
    budget = 4 * 1024 * 1024
    rows = max(8, budget // max(1, 4 * hidden * 4))
    rows = min(rows, 512)
    rows = max(8, (rows // 8) * 8)
    return rows


def _ln_stats(x):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    return mean, var


# ---------------------------------------------------------------------------
# kernel bodies
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps, affine):
    x = x_ref[...].astype(jnp.float32)
    mean, var = _ln_stats(x)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if affine:
        y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(dy_ref, x_ref, w_ref, dx_ref, *, eps, affine):
    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mean, var = _ln_stats(x)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    wdy = dy * w_ref[...].astype(jnp.float32) if affine else dy
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _rms_fwd_kernel(x_ref, w_ref, y_ref, *, eps, affine):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    if affine:
        y = y * w_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _rms_bwd_kernel(dy_ref, x_ref, w_ref, dx_ref, *, eps, affine):
    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    wdy = dy * w_ref[...].astype(jnp.float32) if affine else dy
    c = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - xhat * c) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)


def pallas_rowwise(kernel, outs_dtype, x2d, *vectors, interpret=False):
    """Launch a row-blocked kernel: x2d [n, h] gridded over rows, each
    vector arg [h] broadcast to every block (a same-shape [n, h] arg —
    the backward's dy — rides the row grid instead)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h = x2d.shape
    rb = _row_block(n, h)
    grid = (pl.cdiv(n, rb),)
    in_specs = [pl.BlockSpec((rb, h), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)]
    args = [x2d]
    for v in vectors:
        if v.ndim == 2 and v.shape[0] == n:
            in_specs.append(pl.BlockSpec((rb, h), lambda i: (i, 0),
                                         memory_space=pltpu.VMEM))
        else:
            in_specs.append(pl.BlockSpec((h,), lambda i: (0,),
                                         memory_space=pltpu.VMEM))
        args.append(v)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((rb, h), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, h), outs_dtype),
        interpret=interpret,
    )(*args)


def _ones(h):
    return jnp.ones((h,), jnp.float32)


# ---------------------------------------------------------------------------
# launchers (consumed by apex_tpu.ops.layer_norm)
# ---------------------------------------------------------------------------

def ln_fwd(x2d, weight, bias, eps, *, interpret=False):
    h = x2d.shape[1]
    affine = weight is not None
    w = weight if affine else _ones(h)
    b = bias if bias is not None else jnp.zeros((h,), jnp.float32)
    kernel = functools.partial(_ln_fwd_kernel, eps=eps, affine=affine)
    return pallas_rowwise(kernel, x2d.dtype, x2d, w, b,
                          interpret=interpret)


def ln_bwd_dx(dy2d, x2d, weight, eps, *, interpret=False):
    h = x2d.shape[1]
    affine = weight is not None
    w = weight if affine else _ones(h)
    kernel = functools.partial(_ln_bwd_kernel, eps=eps, affine=affine)

    def k(x_ref, dy_ref, w_ref, dx_ref):
        kernel(dy_ref, x_ref, w_ref, dx_ref)
    return pallas_rowwise(k, x2d.dtype, x2d, dy2d, w,
                          interpret=interpret)


def rms_fwd(x2d, weight, eps, *, interpret=False):
    h = x2d.shape[1]
    affine = weight is not None
    w = weight if affine else _ones(h)
    kernel = functools.partial(_rms_fwd_kernel, eps=eps, affine=affine)

    def k(x_ref, w_ref, y_ref):
        kernel(x_ref, w_ref, y_ref)
    return pallas_rowwise(k, x2d.dtype, x2d, w, interpret=interpret)


def rms_bwd_dx(dy2d, x2d, weight, eps, *, interpret=False):
    h = x2d.shape[1]
    affine = weight is not None
    w = weight if affine else _ones(h)
    kernel = functools.partial(_rms_bwd_kernel, eps=eps, affine=affine)

    def k(x_ref, dy_ref, w_ref, dx_ref):
        kernel(dy_ref, x_ref, w_ref, dx_ref)
    return pallas_rowwise(k, x2d.dtype, x2d, dy2d, w,
                          interpret=interpret)
