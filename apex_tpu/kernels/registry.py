"""The kernel registry: ONE code path deciding pallas-vs-oracle-vs-
interpret for every hand-written kernel in the tree.

Before this module each kernel family carried its own gating — the
decode kernels shared ``contrib._pallas_gate``, compression had a lazy
copy of it behind ``APEX_TPU_COMPRESS_PALLAS``, layer norm had a third
formulation behind ``APEX_TPU_PALLAS_LN`` — and a fix to backend
detection (or a fleet-wide "turn the kernels off" switch) had no single
place to land. Now every kernel registers here and the decision ladder
is uniform:

1. ``APEX_TPU_DISABLE_PALLAS=1`` — global kill, every kernel off.
2. The kernel's own env var (``APEX_TPU_KERNEL_<NAME>``): ``0`` off,
   anything else an explicit opt-in.
3. The kernel's documented legacy alias (e.g. ``APEX_TPU_PALLAS_LN``
   for the norm kernels, ``APEX_TPU_COMPRESS_PALLAS`` — deprecated,
   one warning per process — for the quantize kernels), same ``0``/on
   semantics.
4. The master switch ``APEX_TPU_KERNELS``: ``0`` turns every
   non-overridden kernel off, ``1`` explicitly opts every kernel in
   (including the default-off ones), unset leaves each kernel at its
   registered default.
5. Runnability: interpreter mode (tests — ``force_interpret``) always
   runs the kernel; otherwise kernels only run on a real TPU backend,
   and a kernel registered ``default=False`` (e.g. layer norm, where
   XLA's own fusion measured faster end-to-end) additionally needs an
   explicit opt-in from one of the env layers above.

``APEX_TPU_KERNELS=0`` therefore reproduces the plain-XLA lowering
bit-identically everywhere — the jnp oracle is not a degraded path, it
is the reference the kernels are tested against.

Telemetry: :meth:`KernelRegistry.dispatch` records per-kernel dispatch
counters and a ``kernel`` JSONL event, but ONLY when the process-wide
metrics registry is enabled — disabled-registry dispatches touch
nothing (the PR-2 zero-overhead-off contract).
"""

import os
import warnings

import jax

_MASTER_ENV = "APEX_TPU_KERNELS"
_GLOBAL_KILL = "APEX_TPU_DISABLE_PALLAS"

# legacy aliases that warn when consulted (once per process, per var)
_DEPRECATED_ENVS = frozenset({"APEX_TPU_COMPRESS_PALLAS"})
_warned_legacy = set()


def _warn_legacy(legacy_env, env_var):
    if legacy_env in _DEPRECATED_ENVS and legacy_env not in _warned_legacy:
        _warned_legacy.add(legacy_env)
        warnings.warn(
            f"{legacy_env} is deprecated; use {env_var} (per-kernel) or "
            f"{_MASTER_ENV} (all kernels) instead",
            DeprecationWarning, stacklevel=3)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


class PallasGate:
    """Per-kernel enable switch (the decision ladder in the module
    docstring). ``env_var=0`` opts out; interpreter mode (tests) wins
    over backend detection; otherwise TPU-only, and ``default=False``
    kernels need an explicit env opt-in even there."""

    def __init__(self, env_var: str, *, default: bool = True,
                 legacy_env=None):
        self.env_var = env_var
        self.default = default
        self.legacy_env = legacy_env
        self.interpret = False

    def force_interpret(self, on: bool):
        self.interpret = bool(on)

    def _env_vote(self):
        """The env-layer decision: True/False when some layer spoke,
        None when everything is unset (fall through to the default)."""
        if os.environ.get(_GLOBAL_KILL, "0") == "1":
            return False
        v = os.environ.get(self.env_var)
        if v is not None:
            return v != "0"
        if self.legacy_env is not None:
            lv = os.environ.get(self.legacy_env)
            if lv is not None:
                _warn_legacy(self.legacy_env, self.env_var)
                return lv != "0"
        master = os.environ.get(_MASTER_ENV)
        if master is not None:
            return master != "0"
        return None

    def enabled(self) -> bool:
        vote = self._env_vote()
        if vote is False:
            return False
        if self.interpret:
            return True
        if not _on_tpu():
            return False
        # on TPU, an unset env stack falls back to the registered
        # default; default-off kernels run only on an explicit opt-in
        return bool(vote) if vote is not None else self.default


def choose_block(cache_len: int, preferred: int):
    """Largest tile size that divides the cache buffer: the preferred
    size, then the 256/128 rungs (a 1280-long buffer should stream in
    256-tiles, not silently lose the kernel), then the whole buffer for
    short caches. None -> no dividing block; caller falls back."""
    if cache_len <= preferred:
        return cache_len
    for b in (preferred, 256, 128):
        if b <= cache_len and cache_len % b == 0:
            return b
    return None


class KernelRegistry:
    """Process-wide table of registered kernels and their gates."""

    def __init__(self):
        self._gates = {}

    def register(self, name: str, *, default: bool = True,
                 legacy_env=None, env_var=None) -> PallasGate:
        """Idempotent: the first registration fixes the gate; later
        calls return it (so module reloads don't reset interpret
        state)."""
        gate = self._gates.get(name)
        if gate is None:
            env = env_var or "APEX_TPU_KERNEL_" + name.upper()
            gate = PallasGate(env, default=default, legacy_env=legacy_env)
            self._gates[name] = gate
        return gate

    def gate(self, name: str) -> PallasGate:
        return self._gates[name]

    def names(self):
        return sorted(self._gates)

    def enabled(self, name: str) -> bool:
        return self._gates[name].enabled()

    def force_interpret(self, on: bool, names=None):
        """Run kernels in interpreter mode regardless of backend (CPU
        tests). ``names=None`` flips every registered gate."""
        for n in (self._gates if names is None else names):
            self._gates[n].force_interpret(on)

    def dispatch(self, name: str, path: str, **fields):
        """Record one kernel dispatch (trace-time, from the wrapper):
        ``path`` is ``"pallas"``, ``"interpret"`` or ``"oracle"``.
        No-op when telemetry is disabled — zero overhead off."""
        from apex_tpu.telemetry.registry import get_registry

        reg = get_registry()
        if not reg.enabled:
            return
        reg.counter("kernels/dispatch").inc()
        reg.counter(f"kernels/{name}/{path}").inc()
        # flat per-(kernel, path) counter: lands in every summary's
        # ``counters`` dict, so bench JSONs prove which path actually
        # ran — a silent oracle fallback shows up as
        # ``kernels/dispatch/<name>_oracle`` instead of vanishing
        reg.counter(f"kernels/dispatch/{name}_{path}").inc()
        reg.event("kernel", "dispatch", kernel=name, path=path, **fields)


_REGISTRY = KernelRegistry()


def get_kernel_registry() -> KernelRegistry:
    return _REGISTRY


def kernel_gate(name: str, **kwargs) -> PallasGate:
    """Register-or-fetch the named kernel's gate on the process-wide
    registry — the one-liner kernel modules use at import time."""
    return _REGISTRY.register(name, **kwargs)


def dispatch_path(gate: PallasGate) -> str:
    """The telemetry label for a dispatch through ``gate``: which of
    the three code paths this call will take."""
    if not gate.enabled():
        return "oracle"
    return "interpret" if gate.interpret else "pallas"


def record_dispatch(name: str, gate: PallasGate, **fields):
    """Convenience: label the path and record it in one call; returns
    True when the Pallas kernel (compiled or interpreted) runs."""
    path = dispatch_path(gate)
    _REGISTRY.dispatch(name, path, **fields)
    return path != "oracle"
