"""Fused multi-tensor Adam / LAMB update kernels over bucket-domain state.

Parity: reference csrc/multi_tensor_adam.cu / multi_tensor_lamb.cu —
the ``multi_tensor_applier`` kernels that run one fused elementwise
pass over a chunked flat view of many tensors instead of launching one
op chain per tensor. On this container the ZeRO optimizers
(:mod:`apex_tpu.contrib.optimizers`) already hold their state as flat
fp32 shards — and since PR 10 the overlapped path holds it as
block-aligned per-bucket buffers — so the multi-tensor marshalling is
already done: the fused kernel is ONE ``pallas_call`` per bucket/shard
viewing the flat buffer as ``[nblocks, 256]`` (the same 256-lane block
domain the int8 compression uses), reading g/p/m/v and writing the
three outputs in a single VMEM pass instead of the ~10-op XLA chain.

Scalars that depend on the traced step (``lr``, the bias corrections)
ride in SMEM; the static hyperparameters are baked into the kernel.
The jnp oracles below are the exact expressions the optimizers ran
before this module existed (same operation order, same promotions), so
the gate-off path is bit-identical to the pre-kernel code and the
interpret-mode kernels are bit-identical to the oracle — the parity
tests assert equality.

LAMB's per-tensor trust ratio needs cross-bucket segment norms, so it
stays OUTSIDE the kernel (the existing segment-sum + scalar-join in
``DistributedFusedLAMB``); the kernel fuses the m/v/update production
(:func:`fused_lamb_mvu`) and the ratio apply remains one jnp multiply.
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.kernels.registry import get_kernel_registry, kernel_gate

GATE_ADAM = kernel_gate("adam", default=True)
GATE_LAMB = kernel_gate("lamb", default=True)

BLOCK = 256      # lanes per row — the compression block domain
_ROWS = 8        # fp32 sublane tile


def _record(name, gate):
    path = ("interpret" if gate.interpret else "pallas") \
        if gate.enabled() else "oracle"
    get_kernel_registry().dispatch(name, path)


def _to_blocks(flat):
    """[n] -> [R, 256] fp32 with R a multiple of the sublane tile; the
    zero pad tail produces zero updates (m=v=0 -> update 0)."""
    n = flat.shape[0]
    rows = -(-n // BLOCK)
    rows = -(-rows // _ROWS) * _ROWS
    out = jnp.pad(flat, (0, rows * BLOCK - n))
    return out.reshape(rows, BLOCK), n


def _blocked_call(kernel, scalars, arrays, n_out, out_dtype, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    blocked = []
    n = None
    for a in arrays:
        b, n = _to_blocks(a)
        blocked.append(b)
    rows = blocked[0].shape[0]
    s = jnp.stack([jnp.asarray(v, jnp.float32) for v in scalars]) \
        .reshape(1, -1)
    spec = pl.BlockSpec((_ROWS, BLOCK), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        kernel,
        grid=(rows // _ROWS,),
        in_specs=[pl.BlockSpec((1, s.shape[1]), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)]
        + [spec] * len(blocked),
        out_specs=[spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((rows, BLOCK), out_dtype)] * n_out,
        interpret=interpret,
    )(s, *blocked)
    return [o.reshape(-1)[:n] for o in outs]


# ---------------------------------------------------------------------------
# Adam
# ---------------------------------------------------------------------------

def _adam_kernel(s_ref, g_ref, p_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, eps, wd, adam_w):
    lr = s_ref[0, 0]
    bc1 = s_ref[0, 1]
    bc2 = s_ref[0, 2]
    g = g_ref[...]
    p = p_ref[...]
    if not adam_w:
        g = g + wd * p
    m = b1 * m_ref[...] + (1 - b1) * g
    v = b2 * v_ref[...] + (1 - b2) * jnp.square(g)
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w and wd != 0:
        update = update + wd * p
    po_ref[...] = p - lr * update
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adam_update(g, p, m, v, *, lr, bc1, bc2, b1, b2, eps,
                      weight_decay, adam_w):
    """One fused Adam update over a flat fp32 shard/bucket: returns
    ``(p_new, m_new, v_new)``. The oracle is byte-for-byte the update
    the ZeRO optimizers ran before the kernel existed."""
    _record("adam", GATE_ADAM)
    if GATE_ADAM.enabled():
        kernel = functools.partial(
            _adam_kernel, b1=b1, b2=b2, eps=eps, wd=weight_decay,
            adam_w=adam_w)
        p_new, m_new, v_new = _blocked_call(
            kernel, (lr, bc1, bc2), (g, p, m, v), 3, jnp.float32,
            GATE_ADAM.interpret)
        return p_new, m_new, v_new
    if not adam_w:
        g = g + weight_decay * p
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w and weight_decay != 0:
        update = update + weight_decay * p
    return p - lr * update, m_new, v_new


# ---------------------------------------------------------------------------
# LAMB (m/v/update production; trust ratio stays on the scalar join)
# ---------------------------------------------------------------------------

def _lamb_kernel(s_ref, g_ref, p_ref, m_ref, v_ref,
                 mo_ref, vo_ref, uo_ref, *, b1, b2, beta3, eps, wd,
                 adam_w):
    bc1 = s_ref[0, 0]
    bc2 = s_ref[0, 1]
    g = g_ref[...]
    p = p_ref[...]
    if not adam_w and wd != 0:
        g = g + wd * p
    m = b1 * m_ref[...] + beta3 * g
    v = b2 * v_ref[...] + (1 - b2) * jnp.square(g)
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w and wd != 0:
        update = update + wd * p
    mo_ref[...] = m
    vo_ref[...] = v
    uo_ref[...] = update


def fused_lamb_mvu(g, p, m, v, *, bc1, bc2, b1, b2, beta3, eps,
                   weight_decay, adam_w):
    """The fused LAMB moment + raw-update pass over a flat shard/bucket:
    returns ``(m_new, v_new, update)``. The per-tensor trust ratio and
    the ``p - lr * ratio * update`` apply stay with the caller — the
    ratio couples buckets through the existing segment-norm scalar
    join, which a bucket-local kernel must not absorb."""
    _record("lamb", GATE_LAMB)
    if GATE_LAMB.enabled():
        kernel = functools.partial(
            _lamb_kernel, b1=b1, b2=b2, beta3=beta3, eps=eps,
            wd=weight_decay, adam_w=adam_w)
        m_new, v_new, update = _blocked_call(
            kernel, (bc1, bc2), (g, p, m, v), 3, jnp.float32,
            GATE_LAMB.interpret)
        return m_new, v_new, update
    if not adam_w and weight_decay != 0:
        g = g + weight_decay * p
    m_new = b1 * m + beta3 * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w and weight_decay != 0:
        update = update + weight_decay * p
    return m_new, v_new, update
