from apex_tpu.data.loader import PrefetchLoader  # noqa: F401

__all__ = ["PrefetchLoader"]
