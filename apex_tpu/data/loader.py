"""Background-prefetching host data loader.

Parity: the reference's ``data_prefetcher`` (examples/imagenet/
main_amp.py:256-290) overlaps H2D copies with compute on a side CUDA
stream. The TPU equivalent overlaps *host-side batch assembly +
device transfer* with the device step: a worker thread assembles batches
(native parallel gather via apex_tpu_C.pack_batch when built) and calls
``jax.device_put`` ahead of consumption, keeping a bounded queue of
in-flight batches so the accelerator never waits on the host.
"""

import queue
import threading

import numpy as np

from apex_tpu import _C


class PrefetchLoader:
    """Wrap a sample iterable into an iterator of device-ready batches.

    Args:
      samples: iterable yielding per-sample pytrees of equally-shaped
        numpy arrays (or (x, y) tuples of arrays).
      batch_size: batch size to assemble.
      prefetch: max number of assembled batches in flight.
      device_put: optional callable applied to each assembled batch on the
        worker thread (e.g. ``jax.device_put`` or a sharding-aware
        ``functools.partial(jax.device_put, device=sharding)``).
      drop_last: drop the trailing partial batch.
    """

    def __init__(self, samples, batch_size, *, prefetch=2, device_put=None,
                 drop_last=True):
        self.samples = samples
        self.batch_size = int(batch_size)
        self.prefetch = int(prefetch)
        self.device_put = device_put
        self.drop_last = drop_last

    def _assemble(self, group):
        first = group[0]
        if isinstance(first, tuple):
            cols = tuple(
                self._assemble([g[i] for g in group])
                for i in range(len(first)))
            return cols
        raw = [np.asarray(g) for g in group]
        for a in raw[1:]:
            # byte count alone can't distinguish e.g. (480,640) from
            # (640,480); the native pack only checks bytes
            if a.shape != raw[0].shape or a.dtype != raw[0].dtype:
                raise ValueError(
                    f"PrefetchLoader: sample shape/dtype mismatch "
                    f"({a.shape} {a.dtype} vs {raw[0].shape} {raw[0].dtype})")
        # note: ascontiguousarray promotes 0-d scalars to (1,); the batch
        # shape comes from the pre-promotion sample shape
        out = np.empty((len(raw),) + raw[0].shape, raw[0].dtype)
        _C.pack_batch([np.ascontiguousarray(a) for a in raw], out)
        return out

    def __iter__(self):
        q = queue.Queue(maxsize=self.prefetch)
        stop = object()
        halt = threading.Event()  # consumer gone: worker must exit
        err = []

        def put(item):
            """Blocking put that aborts when the consumer stopped early."""
            while not halt.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                group = []
                for s in self.samples:
                    group.append(s)
                    if len(group) == self.batch_size:
                        batch = self._assemble(group)
                        if self.device_put is not None:
                            batch = self.device_put(batch)
                        if not put(batch):
                            return
                        group = []
                if group and not self.drop_last:
                    batch = self._assemble(group)
                    if self.device_put is not None:
                        batch = self.device_put(batch)
                    if not put(batch):
                        return
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is stop:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # early break / exception in the consumer: release the worker
            halt.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)
