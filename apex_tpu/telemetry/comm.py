"""Collective accounting: measured per-call payload bytes.

``record_collective`` is called from the collective call sites
(``parallel/distributed._psum_with_policy``, the
``parallel/compression`` paths, the ZeRO optimizers) with the *actual*
payload the op ships — element count, dtype, axis — and folds ring-model
wire bytes into the registry. Because the call sites live inside jitted
step functions, recording happens at **trace time**: exactly once per
compilation, which is exactly once per step of the compiled program —
so the accumulated counters after one trace are the measured per-step
bytes that ``bench.py`` emits as ``measured_comm_bytes_per_step`` and
compares against the analytic ``compression.estimate_allreduce_bytes``
model. Payloads are recorded at their semantic wire width (the int8
psum emulation moves int32 partials through XLA today; the *wire format*
a production quantized collective ships is int8 + scales, and events
carry ``emulated=True`` for honesty — keep this consistent with
``estimate_allreduce_bytes``'s model or measured-vs-modeled drifts).

Ring wire model (bytes each replica transmits, ``w`` = axis size):
  psum (allreduce)   2*(w-1)/w * payload      reduce-scatter + all-gather
  psum_scatter       (w-1)/w   * payload
  all_gather         (w-1)     * shard_bytes  == (w-1)/w * full
  pmax / psum_small  2*(w-1)/w * payload      (scale exchanges)
"""

import numpy as np

from apex_tpu.telemetry.registry import get_registry

# ops whose ``payload`` argument is the per-replica *shard* (each rank
# transmits its shard to the other w-1 ranks)
_SHARD_OPS = {"all_gather"}
# allreduce-shaped ops: two ring phases
_TWO_PHASE_OPS = {"psum", "pmax", "pmin", "all_reduce"}
# point-to-point shifts: every rank ships its whole payload once
# (the ring steps inside kernels/fused_cc price per-step, so g-1
# recorded permutes of payload/g == one reduce-scatter of payload —
# same convention as analysis/sharding.wire_bytes_for)
_FULL_OPS = {"ppermute", "collective_permute"}


def axis_label(axis_name):
    """Canonical event tag for a (possibly tuple) mesh axis name —
    ``"data"``, ``"data,model"``, ``None`` when no axis was named. The
    per-axis rollup (``comm/axis/<label>_bytes`` counters, the
    ``telemetry_report`` comm table) keys on this, which is what makes
    DP compression savings and TP psum volume separable in one report
    on a 2-D mesh."""
    if axis_name is None:
        return None
    if isinstance(axis_name, (tuple, list)):
        return ",".join(str(a) for a in axis_name) or None
    return str(axis_name)


def traced_elements(x):
    """Physical element count of ``x``, batch axes included.

    Inside ``jax.vmap`` a tracer's visible aval is the UNBATCHED view,
    so ``x.size`` under-counts by the batch factor and the trace-time
    accounting would drift below the lowered HLO's batched collectives
    (static==measured per axis is a checked invariant — the serving
    decode body psums under a slot vmap). Unwrap the batch-tracer
    chain and count the underlying value's shape instead."""
    val = x
    try:
        from jax.interpreters import batching
        while isinstance(val, batching.BatchTracer):
            val = val.val
    except Exception:
        val = x
    return int(np.prod([int(d) for d in np.shape(val)]))


def axis_world(axis_name):
    """Concrete size of a (possibly tuple) mesh axis, resolved at trace
    time; 1 when no axis is bound (single-device fallback paths)."""
    from jax import lax

    try:
        if isinstance(axis_name, (tuple, list)):
            n = 1
            for a in axis_name:
                n *= lax.axis_size(a)
            return int(n)
        return int(lax.axis_size(axis_name))
    except Exception:
        return 1


def wire_bytes(op, payload_bytes, world):
    """Ring-model bytes each replica transmits for one collective."""
    if world <= 1:
        return 0.0
    if op in _TWO_PHASE_OPS:
        return 2.0 * (world - 1) / world * payload_bytes
    if op in _SHARD_OPS:
        return float((world - 1) * payload_bytes)
    if op in _FULL_OPS:
        return float(payload_bytes)
    # psum_scatter and anything one-phase
    return (world - 1) / world * payload_bytes


def record_collective(op, *, elements, dtype, axis_name=None, world=None,
                      mode=None, emulated=False, registry=None,
                      bits=None):
    """Account one collective call (host-side, trace-time).

    ``elements``/``dtype`` describe the semantic wire payload;
    ``world`` may be passed when the caller already resolved the axis
    size (the ZeRO optimizers), else it is read from ``axis_name`` via
    ``lax.axis_size`` (static under tracing). ``bits`` overrides the
    dtype's width for sub-byte wire formats (the int4 psum emulation
    records its int8-valued codes at 4 bits/element — the width a
    production packed collective ships). No-op when the registry is
    disabled or no axis spans more than one device.
    """
    reg = registry or get_registry()
    if not reg.enabled:
        return 0.0
    if world is None:
        world = axis_world(axis_name)
    itemsize = bits / 8.0 if bits else np.dtype(dtype).itemsize
    payload = float(elements) * itemsize
    wire = wire_bytes(op, payload, world)
    label = axis_label(axis_name)
    reg.counter("comm/calls").inc()
    reg.counter("comm/bytes").inc(wire)
    reg.counter(f"comm/{op}_bytes").inc(wire)
    reg.counter(f"comm/dtype/{np.dtype(dtype).name}_bytes").inc(wire)
    if label is not None:
        # per-mesh-axis rollup: on a 2-D (data, model) mesh this is
        # what separates compressed DP grad bytes from fp32 TP
        # activation bytes in one report
        reg.counter(f"comm/axis/{label}_bytes").inc(wire)
    reg.event("collective", op, elements=int(elements),
              dtype=np.dtype(dtype).name, world=int(world),
              payload_bytes=int(payload), wire_bytes=int(round(wire)),
              mode=mode, emulated=bool(emulated) or None,
              bits=int(bits) if bits else None, axis=label)
    return wire
