"""Compile watch: make silent recompilation a first-class, observable
event.

The failure mode: XLA recompiles whenever a jitted function sees a new
abstract signature — a shape-unstable input pipeline, a Python scalar
whose type drifts, a sharding that flips between calls — and on TPU a
large-model compile costs minutes. A per-step retrace therefore turns a
"fast" run into one that spends 99% of wall-clock in the compiler while
the step-time telemetry (PR 2) sees only mysteriously slow steps: the
compile itself was invisible. This module is the missing signal:

- :class:`CompileWatcher` — wrap a jitted callable with
  :meth:`~CompileWatcher.watch`; every call snapshots the pjit cache
  size (``fn._cache_size()``), so a cache-size increase IS a
  trace+compile, attributed to exactly that call. On a *re*compile the
  watcher diffs the new abstract signature (per-argument shapes /
  dtypes / weak-types / named shardings / Python-scalar values) against
  the cached one and emits a ``compile`` JSONL event naming exactly
  which argument changed (path, old -> new). Metrics land in the
  existing registry: ``compile/count`` / ``compile/seconds`` counters
  (fed by a ``jax.monitoring`` listener, so they also count compiles of
  *unwatched* functions) plus per-function ``compile/count/<name>``.
- :func:`assert_no_recompiles` — the test/CI primitive: a context
  manager that counts backend compiles across the block (via the same
  monitoring listener) and raises :class:`RecompileError` when any
  happened, naming the changed argument when a watched function saw it.
  Wrap N steady-state steps after warmup and any future per-step
  retrace fails tier-1 loudly.

Everything is host-side: watching never touches the traced program, so
the lowered HLO of a watched step is byte-identical to the unwatched
one (asserted in tests/L0/test_compile_watch.py — the same contract the
numerics layer keeps).

Opt-in: ``APEX_TPU_COMPILE_WATCH=1`` enables the process-global watcher
returned by :func:`get_watcher` (``bench.py ddp_memwatch`` enables it
programmatically); a disabled watcher's ``watch`` returns the function
unchanged — zero overhead off. :func:`assert_no_recompiles` works
regardless of the opt-in (tests should not depend on env state).
"""

import contextlib
import os
import threading
import time

from apex_tpu.telemetry.registry import get_registry

ENV_WATCH = "APEX_TPU_COMPILE_WATCH"
# opt-in for the static HLO lint pass (apex_tpu.analysis,
# docs/analysis.md): an enabled watcher lints every newly compiled
# executable it sees and emits `lint` JSONL events per finding
ENV_LINT = "APEX_TPU_HLO_LINT"

# jax.monitoring event names (stable across the jax 0.4.x line; probed
# in tests). backend_compile fires once per XLA compilation, with the
# compile wall-time as the duration.
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RecompileError(RuntimeError):
    """Raised by :func:`assert_no_recompiles` when a compile happened
    inside the guarded block."""


# -- process-wide backend compile accounting --------------------------------

_MONITOR_LOCK = threading.Lock()
_MONITOR_INSTALLED = False
_BACKEND = {"count": 0, "seconds": 0.0}


def _on_backend_compile(event, duration, **kwargs):
    if not event.endswith("backend_compile_duration"):
        return
    with _MONITOR_LOCK:
        _BACKEND["count"] += 1
        _BACKEND["seconds"] += float(duration)
    reg = get_registry()
    if reg.enabled:
        reg.counter("compile/count").inc()
        reg.counter("compile/seconds").inc(float(duration))


def install_monitoring():
    """Register the (one, idempotent) ``jax.monitoring`` listener that
    feeds :func:`backend_compiles` and the ``compile/count`` /
    ``compile/seconds`` registry counters. jax offers no per-listener
    removal, so this registers exactly once per process and the listener
    stays — it is a counter bump, nanoseconds per compile."""
    global _MONITOR_INSTALLED
    with _MONITOR_LOCK:
        if _MONITOR_INSTALLED:
            return
        _MONITOR_INSTALLED = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(
        _on_backend_compile)


def backend_compiles():
    """``(count, total_seconds)`` of XLA backend compiles observed since
    :func:`install_monitoring` ran (process-wide, watched or not)."""
    with _MONITOR_LOCK:
        return _BACKEND["count"], _BACKEND["seconds"]


# -- abstract signatures ----------------------------------------------------

def _leaf_path_str(path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _describe_leaf(x):
    """One stable string per argument leaf — everything that can key a
    retrace: shape/dtype/weak-type for arrays, the named-sharding spec
    when one is attached (a resharded input retraces), and the VALUE of
    Python scalars/strings (value-keyed when the arg is static; for a
    traced weak-typed scalar the extra precision is harmless because
    diffs are only taken on calls that did compile)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            desc = f"{dtype.name if hasattr(dtype, 'name') else dtype}" \
                   f"{list(shape)}"
        except Exception:
            desc = f"{dtype}[?]"
        if getattr(x, "weak_type", False):
            desc += "~"
        sharding = getattr(x, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            desc += f"@{spec}"
        return desc
    if isinstance(x, (bool, int, float, complex, str, bytes, type(None))):
        return f"py:{type(x).__name__}={x!r}"
    return f"static:{type(x).__name__}"


def abstract_signature(args, kwargs=None):
    """``{arg_path: descriptor}`` for a call's arguments — the host-side
    mirror of the signature jit keys its cache on. Paths are '/'-joined
    pytree paths under ``args/<i>`` / ``kwargs/<name>``."""
    import jax

    sig = {}
    for root, tree in (("args", tuple(args)),
                       ("kwargs", dict(kwargs or {}))):
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                tree, is_leaf=lambda l: l is None)[0]:
            sig[f"{root}/{_leaf_path_str(path)}"] = _describe_leaf(leaf)
    return sig


def diff_signatures(old, new):
    """Per-argument changes between two :func:`abstract_signature`
    dicts: ``[{"arg", "old", "new"}, ...]`` (``None`` marks an
    added/removed argument), sorted by argument path."""
    changes = []
    for key in sorted(set(old) | set(new)):
        a, b = old.get(key), new.get(key)
        if a != b:
            changes.append({"arg": key, "old": a, "new": b})
    return changes


# -- the watcher ------------------------------------------------------------

class _FnStats:
    __slots__ = ("name", "signature", "compiles", "recompiles",
                 "compile_seconds", "last_change")

    def __init__(self, name):
        self.name = name
        self.signature = None
        self.compiles = 0
        self.recompiles = 0
        self.compile_seconds = 0.0
        self.last_change = None


def _cache_size(fn):
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class _WatchedFunction:
    """Host-side wrapper around one jitted callable. Delegates every
    attribute (``lower``, ``_cache_size``, ...) to the wrapped function,
    so it drops into code that uses the AOT API."""

    def __init__(self, fn, name, watcher):
        self._fn = fn
        self._name = name
        self._watcher = watcher
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        w = self._watcher
        if not w.enabled:
            return self._fn(*args, **kwargs)
        before = _cache_size(self._fn)
        nb_before = backend_compiles()[0]
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        after = _cache_size(self._fn)
        if after is not None and before is not None:
            compiled = after > before
        else:  # no pjit cache introspection: fall back to process count
            compiled = backend_compiles()[0] > nb_before
        if compiled:
            w._on_compile(self._name, abstract_signature(args, kwargs), dt)
            w._maybe_lint(self._name, self._fn, args, kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


class CompileWatcher:
    """Trace/compile accounting for jitted functions (host-side only).

    Usable three ways: as a plain object (``w = CompileWatcher();
    step = w.watch(step)``), as a context manager (the exit emits a
    ``compile`` summary event covering the block), and process-globally
    via :func:`get_watcher` + ``APEX_TPU_COMPILE_WATCH=1``. A disabled
    watcher's ``watch`` returns the function unchanged.
    """

    def __init__(self, *, enabled=None, registry=None, lint=None):
        if enabled is None:
            enabled = os.environ.get(ENV_WATCH, "") not in ("", "0")
        if lint is None:
            lint = os.environ.get(ENV_LINT, "") not in ("", "0")
        self.enabled = bool(enabled)
        self.lint_enabled = bool(lint)
        self._registry = registry
        self.functions = {}
        self.lint_reports = {}
        self._entered_at = None
        if self.enabled:
            install_monitoring()

    # -- enablement ---------------------------------------------------------

    def enable(self):
        self.enabled = True
        install_monitoring()
        return self

    def disable(self):
        self.enabled = False
        return self

    def _reg(self):
        return self._registry or get_registry()

    # -- watching -----------------------------------------------------------

    def watch(self, fn, name=None):
        """Wrap ``fn`` (typically a jitted callable) so every
        trace+compile is counted, timed, and — when it is a recompile —
        signature-diffed. Returns ``fn`` itself when disabled."""
        if not self.enabled:
            return fn
        if name is None:
            name = getattr(fn, "__name__", None) or repr(fn)
        self.functions.setdefault(name, _FnStats(name))
        return _WatchedFunction(fn, name, self)

    def _on_compile(self, name, signature, call_seconds):
        rec = self.functions.setdefault(name, _FnStats(name))
        rec.compiles += 1
        rec.compile_seconds += call_seconds
        changed = None
        if rec.signature is not None:  # a RE-compile: name the culprit
            rec.recompiles += 1
            changed = diff_signatures(rec.signature, signature)
            rec.last_change = changed
        rec.signature = signature
        reg = self._reg()
        if reg.enabled:
            reg.counter(f"compile/count/{name}").inc()
            reg.histogram("compile/call_seconds").observe(call_seconds)
            reg.event("compile", name,
                      compiles=rec.compiles,
                      recompile=rec.recompiles > 0 and changed is not None,
                      call_seconds=round(call_seconds, 6),
                      changed=changed)

    # -- HLO lint (apex_tpu.analysis; APEX_TPU_HLO_LINT=1) ------------------

    def _maybe_lint(self, name, fn, args, kwargs, *, lowered=None):
        """Lint the program that just compiled and emit ``lint`` events.
        Never raises: a lint crash is a telemetry gap, not a training
        failure. Reports accumulate in ``self.lint_reports``."""
        if not (self.enabled and self.lint_enabled):
            return None
        from apex_tpu import analysis

        try:
            if lowered is not None:
                report = analysis.lint_lowered(lowered, name=name)
            else:
                report = analysis.lint_fn(fn, *args, name=name,
                                          **(kwargs or {}))
        except Exception as e:  # noqa: BLE001 — lint must never kill a run
            reg = self._reg()
            if reg.enabled:
                reg.event("lint", name, error=f"{type(e).__name__}: "
                                             f"{str(e)[:200]}")
            return None
        self.lint_reports[name] = report
        analysis.report_to_registry(report, registry=self._registry,
                                    name=name)
        return report

    def lint_violation_count(self):
        """Total findings across every lint this watcher ran."""
        return sum(len(r.findings) for r in self.lint_reports.values())

    def record_aot(self, name, args=(), kwargs=None, *, seconds=0.0,
                   lowered=None):
        """Register an ahead-of-time compile (``jit(...).lower(args)
        .compile()`` — the ServeEngine startup path) under ``name``.

        AOT executables never pass through :meth:`watch`'s cache-size
        probe (calling one cannot compile), so the startup compile is
        recorded explicitly here: it lands in the same per-function
        stats, ``compile`` JSONL events, and signature bookkeeping as a
        watched jit compile — and a second ``record_aot`` under the
        same name with a different signature shows up as a named
        recompile, exactly like a drifting jit signature would.

        ``lowered`` (the pre-compile ``Lowered``) opts the AOT compile
        into the HLO lint pass when ``APEX_TPU_HLO_LINT=1`` — the
        ServeEngine passes each ladder entry's lowering here so the
        serving executables are linted without a second trace."""
        if not self.enabled:
            return
        self._on_compile(name, abstract_signature(args, kwargs), seconds)
        if lowered is not None:
            self._maybe_lint(name, None, (), None, lowered=lowered)

    # -- accounting ---------------------------------------------------------

    def compile_count(self, name=None):
        """Compiles of one watched function (or the sum over all)."""
        if name is not None:
            rec = self.functions.get(name)
            return rec.compiles if rec else 0
        return sum(r.compiles for r in self.functions.values())

    def recompile_count(self):
        return sum(r.recompiles for r in self.functions.values())

    def last_changes(self):
        """``{fn_name: [{"arg", "old", "new"}, ...]}`` for every watched
        function whose latest compile was a signature-diffed recompile."""
        return {n: r.last_change for n, r in self.functions.items()
                if r.last_change}

    # -- context manager ----------------------------------------------------

    def __enter__(self):
        self.enable()
        self._entered_at = backend_compiles()
        return self

    def __exit__(self, *exc):
        count0, secs0 = self._entered_at or (0, 0.0)
        count1, secs1 = backend_compiles()
        reg = self._reg()
        if reg.enabled:
            reg.event("compile", "watch_summary",
                      backend_compiles=count1 - count0,
                      backend_compile_seconds=round(secs1 - secs0, 6),
                      watched={n: {"compiles": r.compiles,
                                   "recompiles": r.recompiles}
                               for n, r in self.functions.items()})
        return False


_GLOBAL = None
_GLOBAL_LOCK = threading.Lock()


def get_watcher():
    """The process-global watcher, created on first use — enabled iff
    ``APEX_TPU_COMPILE_WATCH`` was set at that point (call
    ``get_watcher().enable()`` to opt in programmatically, as
    ``bench.py ddp_memwatch`` does)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = CompileWatcher()
    return _GLOBAL


@contextlib.contextmanager
def assert_no_recompiles(watcher=None, *, allow=0):
    """Fail loudly if anything compiled inside the block.

    The test/CI primitive for shape stability: warm the step up, then
    run N steady-state steps under this context — any retrace (a Python
    scalar leaking into the traced signature, a drifting input shape, a
    flipped sharding) raises :class:`RecompileError`. Counting is
    process-wide via the ``jax.monitoring`` backend-compile listener,
    so even compiles of helpers you forgot to watch are caught; when a
    watched function saw the recompile, the error names the changed
    argument (path, old -> new). ``allow`` tolerates that many compiles
    (e.g. a known one-off lazy init inside the block)."""
    install_monitoring()
    watcher = watcher or get_watcher()
    before = backend_compiles()[0]
    marks = {n: r.recompiles for n, r in watcher.functions.items()}
    yield watcher
    delta = backend_compiles()[0] - before
    if delta <= allow:
        return
    detail = ""
    for name, rec in watcher.functions.items():
        if rec.recompiles > marks.get(name, 0) and rec.last_change:
            first = rec.last_change[0]
            detail = (f" Watched fn '{name}' recompiled: argument "
                      f"'{first['arg']}' changed "
                      f"{first['old']} -> {first['new']}.")
            break
    raise RecompileError(
        f"{delta} XLA compile(s) happened inside an "
        f"assert_no_recompiles block (allowed {allow}) — something is "
        f"retracing per call; check input shapes/dtypes and Python "
        f"scalars reaching the jitted signature.{detail}")
