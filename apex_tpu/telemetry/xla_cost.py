"""XLA cost accounting: FLOPs / bytes-accessed for a jitted step, and
achieved MFU / HBM-utilization against a per-backend peak table.

``step_cost(jitted, *args)`` extracts XLA's own cost analysis from the
lowered (or compiled) computation — the measured counterpart to the
analytic FLOP formulas in ``bench.py``. By default it stops at
``.lower(...)``: the trace-only HLO cost analysis avoids paying a second
compilation (the jit call's own compile is cached separately, and a
large model can take tens of minutes to compile on this host's 1-core
CPU). Pass ``use_compiled=True`` for post-optimization numbers when a
compile is acceptable (or already cached).

The peak table is deliberately small: per-backend (peak FLOP/s, peak
HBM bytes/s), overridable via ``APEX_TPU_PEAK_TFLOPS`` and
``APEX_TPU_PEAK_HBM_GBPS``. The TPU default is the measured 154 bf16
TFLOP/s of this chip class (PERF.md), matching ``bench.py``.
"""

import os

# (peak_flops_per_sec, peak_hbm_bytes_per_sec) by jax backend platform.
# CPU numbers are order-of-magnitude placeholders — the CPU mesh exists
# for tests, not rooflines.
_PEAK_DEFAULTS = {
    "tpu": (154e12, 1.23e12),
    "gpu": (312e12, 2.0e12),
    "cpu": (0.1e12, 0.05e12),
}


def peak_table(backend=None):
    """(peak_flops_per_sec, peak_hbm_bytes_per_sec) for ``backend``
    (default: the current jax default backend), honoring the env
    overrides."""
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    flops, hbm = _PEAK_DEFAULTS.get(backend, _PEAK_DEFAULTS["tpu"])
    env_flops = os.environ.get("APEX_TPU_PEAK_TFLOPS")
    if env_flops:
        flops = float(env_flops) * 1e12
    env_hbm = os.environ.get("APEX_TPU_PEAK_HBM_GBPS")
    if env_hbm:
        hbm = float(env_hbm) * 1e9
    return flops, hbm


def _normalize(analysis):
    """XLA returns a dict (Lowered) or a list of per-computation dicts
    (Compiled); collapse to {"flops", "bytes_accessed"} floats."""
    if analysis is None:
        return None
    if not isinstance(analysis, dict):
        entries = [a for a in analysis if isinstance(a, dict)]
        if not entries:
            return None
        analysis = entries[0]
    return {
        "flops": float(analysis.get("flops", 0.0)),
        "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
    }


def cost_from_lowered(lowered, use_compiled=False):
    """Cost analysis of an already-``.lower()``-ed computation (lets a
    caller that also wants ``memory_analysis`` pay for one lowering,
    not two — see ``bench._measure_step_cost``)."""
    if use_compiled:
        try:
            return _normalize(lowered.compile().cost_analysis())
        except Exception:
            pass
    try:
        return _normalize(lowered.cost_analysis())
    except Exception:
        return None


def step_cost(jitted, *args, use_compiled=False, **kwargs):
    """Cost analysis of one invocation of ``jitted(*args, **kwargs)``:
    ``{"flops", "bytes_accessed"}``, or None when the backend offers no
    analysis. Lowering re-traces the function (host-side only — safe on
    donated/deleted example arrays since only avals are read)."""
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception:
        return None
    return cost_from_lowered(lowered, use_compiled=use_compiled)


def utilization(flops_per_step, step_seconds, *, bytes_per_step=None,
                backend=None):
    """Achieved fractions of peak: ``{"mfu", "hbm_util", ...}``.

    ``mfu`` = model FLOP/s over peak FLOP/s (PaLM convention — pass
    model FLOPs, not hardware FLOPs, if you want the classic MFU);
    ``hbm_util`` = bytes-accessed/s over peak HBM bandwidth (an upper
    bound on demand — XLA's bytes-accessed counts every operand touch,
    not DRAM traffic)."""
    peak_flops, peak_hbm = peak_table(backend)
    out = {
        "flops_per_sec": flops_per_step / step_seconds,
        "mfu": flops_per_step / step_seconds / peak_flops,
    }
    if bytes_per_step is not None:
        out["bytes_per_sec"] = bytes_per_step / step_seconds
        out["hbm_util"] = bytes_per_step / step_seconds / peak_hbm
    return out


def record_step_cost(cost, step_seconds, *, registry=None, backend=None):
    """Fold a :func:`step_cost` result + measured step time into the
    registry: ``mfu`` / ``hbm_util`` / ``model_flops_per_step_xla``
    gauges. Returns the :func:`utilization` dict (or None)."""
    from apex_tpu.telemetry.registry import get_registry

    if cost is None or not step_seconds:
        return None
    util = utilization(cost["flops"], step_seconds,
                       bytes_per_step=cost.get("bytes_accessed"),
                       backend=backend)
    reg = registry or get_registry()
    if reg.enabled:
        reg.gauge("model_flops_per_step_xla").set(cost["flops"])
        reg.gauge("mfu").set(util["mfu"])
        if "hbm_util" in util:
            reg.gauge("hbm_util").set(util["hbm_util"])
        reg.event("xla_cost", "step",
                  flops=cost["flops"],
                  bytes_accessed=cost.get("bytes_accessed"),
                  step_seconds=step_seconds,
                  mfu=util["mfu"], hbm_util=util.get("hbm_util"))
    return util
