"""apex_tpu.telemetry — unified tracing, metrics, and XLA cost accounting.

The observability layer under the parallel/optimizer/bench stack:

- :mod:`registry`  — process-wide counters/gauges/histograms + a JSONL
  event sink under ``$APEX_TPU_TELEMETRY_DIR`` (rank-aware).
- :mod:`trace`     — named :func:`span` context managers (optional
  device-sync fencing, nested under ``jax.profiler.TraceAnnotation`` /
  ``jax.named_scope``), causal identity (:class:`TraceContext` on a
  contextvar; spans emit begin/end events carrying
  trace/span/parent ids — the substrate ``tools/trace_export.py``
  turns into a Perfetto-loadable Chrome trace), and a
  ``start_profiler_trace``/``stop`` pair gated by
  ``APEX_TPU_PROFILE_DIR``.
- :mod:`xla_cost`  — ``lower().cost_analysis()`` extraction for a
  jitted step + achieved MFU / HBM-utilization against a per-backend
  peak table.
- :mod:`comm`      — measured collective accounting (per-call payload
  dtype/bytes from ``_psum_with_policy`` and the compression paths),
  the measured counterpart to ``compression.estimate_allreduce_bytes``.
- :mod:`numerics`  — jit-native per-layer gradient/activation stats
  (:func:`~apex_tpu.telemetry.numerics.tensor_stats` /
  :func:`~apex_tpu.telemetry.numerics.tree_stats`): norms, zero
  fraction, non-finite counts, fp16/bf16 under/overflow fractions —
  computed entirely in-graph.
- :mod:`recorder`  — :class:`~apex_tpu.telemetry.recorder.FlightRecorder`,
  a device-side ring buffer of the last K steps' stats, fetched once
  for a ``numerics-postmortem-rank<N>.json`` when the resilience guard
  trips.
- :mod:`monitor`   — the live control plane
  (:class:`~apex_tpu.telemetry.monitor.Monitor`): rolling windows over
  registry snapshots + tailed cross-rank JSONL, a declarative
  :class:`~apex_tpu.telemetry.monitor.AlertRule` table with
  firing/resolved ``alert`` events, OpenMetrics exposition
  (:func:`~apex_tpu.telemetry.monitor.render_openmetrics`, scrape
  endpoint gated by ``APEX_TPU_MONITOR_PORT``), and the
  ``tools/monitor_dash.py`` terminal view.
- :mod:`attribution` — online 3-D-mesh attribution
  (:class:`~apex_tpu.telemetry.attribution.PipelineAttributor`):
  exposure-difference straggler detection over ``pp_tick_<t>`` spans,
  measured vs analytic bubble fraction, per-axis exposed-comm split.
- :mod:`compile_watch` — trace/compile accounting per jitted function
  (:class:`~apex_tpu.telemetry.compile_watch.CompileWatcher`):
  ``compile`` events that name exactly which argument changed on a
  recompile, ``compile/count``/``compile/seconds`` counters, and the
  :func:`~apex_tpu.telemetry.compile_watch.assert_no_recompiles`
  test primitive. Opt-in via ``APEX_TPU_COMPILE_WATCH=1``.
- :mod:`memory`    — HBM budget accounting:
  :func:`~apex_tpu.telemetry.memory.step_memory` (XLA
  ``memory_analysis()`` -> peak bytes + ``memory/hbm_headroom``
  gauge), :func:`~apex_tpu.telemetry.memory.live_buffer_census`,
  :func:`~apex_tpu.telemetry.memory.preflight`, and the
  ``memory-postmortem-rank<N>.json`` OOM handler
  (:func:`~apex_tpu.telemetry.memory.oom_guard`).

Everything is host-side: recording inside jitted code happens at trace
time (once per compilation == once per step of the compiled program)
and never inserts callbacks into compiled programs. Disabled — the
default, when ``APEX_TPU_TELEMETRY_DIR`` is unset and nothing called
``enable()`` — every instrument is a shared no-op.

Quickstart (docs/observability.md has the full tour)::

    APEX_TPU_TELEMETRY_DIR=/tmp/tel python bench.py ddp_compressed
    python tools/telemetry_report.py /tmp/tel
"""

from apex_tpu.telemetry.registry import (  # noqa: F401
    MetricsRegistry,
    get_registry,
    set_registry,
    use_registry,
)
from apex_tpu.telemetry.trace import (  # noqa: F401
    Span,
    TraceContext,
    current_trace,
    device_sync,
    emit_flow,
    emit_span,
    new_span_id,
    new_trace_id,
    span,
    start_profiler_trace,
    stop_profiler_trace,
    trace_context,
)
from apex_tpu.telemetry import comm  # noqa: F401
from apex_tpu.telemetry import compile_watch  # noqa: F401
from apex_tpu.telemetry import memory  # noqa: F401
from apex_tpu.telemetry import numerics  # noqa: F401
from apex_tpu.telemetry import recorder  # noqa: F401
from apex_tpu.telemetry import xla_cost  # noqa: F401
from apex_tpu.telemetry.attribution import (  # noqa: F401
    PipelineAttributor,
)
from apex_tpu.telemetry.compile_watch import (  # noqa: F401
    CompileWatcher,
    RecompileError,
    assert_no_recompiles,
)
from apex_tpu.telemetry.monitor import (  # noqa: F401
    AlertRule,
    JsonlTailer,
    Monitor,
    default_rules,
    parse_openmetrics,
    render_openmetrics,
)
from apex_tpu.telemetry.memory import (  # noqa: F401
    HBMExhaustedError,
    MemoryBudgetError,
    live_buffer_census,
    oom_guard,
    oom_postmortem,
    preflight,
    step_memory,
)
from apex_tpu.telemetry.numerics import (  # noqa: F401
    TensorStats,
    tensor_stats,
    tree_stats,
)
from apex_tpu.telemetry.recorder import (  # noqa: F401
    FlightRecorder,
    RecorderState,
)
