"""Online straggler / bubble / exposed-comm attribution for the 3-D mesh.

The pipeline step (``parallel/pipeline.py``) is host-unrolled: every
1F1B tick runs under a ``pp_tick_<t>`` span stamped with the tick's
schedule entry — ``phase`` and the ``fwd``/``bwd`` ``[rank,
microbatch]`` unit lists. Those spans are the only per-tick timing the
stack emits, and they cover *all* stages of a tick at once (one SPMD
program), so a per-stage time cannot be read off directly. This module
recovers it online with an **exposure-difference estimator**:

    for stage r:  delta(r) = mean(tick duration | r active)
                           - mean(tick duration | r inactive)

Identifiability comes from the 1F1B ramp itself: warmup ticks run
without the late stages and cooldown ticks without the early ones, so
every stage has both exposed and unexposed ticks (except at ``pp == 1``
or when too few ticks were seen — then the estimator abstains rather
than guess). A stage whose work is slow lengthens exactly the ticks it
appears in, so its delta stands out; :meth:`PipelineAttributor.report`
names the stage with the largest delta once it clears both a relative
and an absolute floor.

The same span stream yields two more online fractions:

- **measured bubble fraction** — duration-weighted idle stage-slots,
  ``sum(dur_t * idle_stages_t) / (pp * sum(dur_t))``; its analytic
  counterpart is ``(pp-1)/(m+pp-1)``
  (:func:`~apex_tpu.parallel.pipeline.analytic_bubble_fraction`).
- **per-axis comm exposure** — ``ddp_overlap_bucket_<n>`` spans are the
  ``data``-axis gradient collectives; a span carrying ``bubble=True``
  was traced into the cooldown bubble region (overlappable, counted
  *hidden*), one without rides the critical path (counted *exposed*).
  ``pipe``-axis exposure is the bubble fraction itself — idle stage
  slots are exactly where pipe transfers are not hidden by compute.

Like every telemetry reader, the attributor consumes plain event
*records* (the dicts the registry taps/sinks carry) — it works
identically fed live from a :class:`~apex_tpu.telemetry.monitor
.Monitor` tap or offline from parsed JSONL lines, and it never touches
compiled programs.
"""

import collections

_TICK_PREFIX = "pp_tick_"
_BUCKET_PREFIX = "ddp_overlap_bucket_"


def _units(rec, key):
    """The ``[rank, microbatch]`` unit list of a tick record, tolerant
    of JSON round-trips (lists) and live records (lists of lists)."""
    out = []
    for u in rec.get(key) or ():
        try:
            out.append((int(u[0]), int(u[1])))
        except (TypeError, ValueError, IndexError):
            continue
    return out


class PipelineAttributor:
    """Streaming consumer of ``pp_tick_<t>`` / ``ddp_overlap_bucket_<n>``
    span records; :meth:`report` computes the attribution on demand.

    Bounded state: the last ``max_ticks`` tick observations (a repeated
    step re-traces nothing — ticks fire at trace time — so the window
    covers every tick of the latest compilation and then some).
    """

    def __init__(self, *, max_ticks=4096):
        self._ticks = collections.deque(maxlen=max_ticks)
        self._pp = 0
        self._microbatches = 0
        self._comm = {"hidden_s": 0.0, "exposed_s": 0.0,
                      "hidden_n": 0, "exposed_n": 0}

    # -- intake -------------------------------------------------------------

    def add_span(self, rec):
        """Feed one ``span`` event record; non-matching spans are
        ignored, so the whole event stream can be piped through.
        Returns True iff the record was consumed."""
        if rec.get("kind") != "span":
            return False
        name = rec.get("name", "")
        if name.startswith(_TICK_PREFIX):
            return self._add_tick(rec)
        if name.startswith(_BUCKET_PREFIX):
            return self._add_bucket(rec)
        return False

    def _add_tick(self, rec):
        try:
            dur = float(rec["duration_s"])
        except (KeyError, TypeError, ValueError):
            return False
        fwd = _units(rec, "fwd")
        bwd = _units(rec, "bwd")
        active = {r for r, _ in fwd} | {r for r, _ in bwd}
        for r in active:
            self._pp = max(self._pp, r + 1)
        for _, mb in fwd + bwd:
            self._microbatches = max(self._microbatches, mb + 1)
        self._ticks.append((dur, frozenset(active),
                            rec.get("phase", "")))
        return True

    def _add_bucket(self, rec):
        try:
            dur = float(rec["duration_s"])
        except (KeyError, TypeError, ValueError):
            return False
        if rec.get("bubble"):
            self._comm["hidden_s"] += dur
            self._comm["hidden_n"] += 1
        else:
            self._comm["exposed_s"] += dur
            self._comm["exposed_n"] += 1
        return True

    # -- reporting ----------------------------------------------------------

    @property
    def ticks_seen(self):
        return len(self._ticks)

    def report(self, *, rel_threshold=0.5, min_delta_s=0.001):
        """The attribution snapshot.

        ``straggler`` is the stage with the largest exposure delta,
        or None when no stage clears ``max(rel_threshold *
        mean_inactive, min_delta_s)`` with at least one tick on each
        side of the split (the abstain case: uniform load, pp == 1, or
        not enough ticks yet).
        """
        pp = self._pp
        ticks = list(self._ticks)
        per_stage = []
        straggler = None
        best_delta = 0.0
        for r in range(pp):
            act = [d for d, a, _ in ticks if r in a]
            inact = [d for d, a, _ in ticks if r not in a]
            mean_a = sum(act) / len(act) if act else None
            mean_i = sum(inact) / len(inact) if inact else None
            delta = (mean_a - mean_i
                     if mean_a is not None and mean_i is not None
                     else None)
            per_stage.append({
                "stage": r,
                "active_ticks": len(act),
                "inactive_ticks": len(inact),
                "mean_active_s": mean_a,
                "mean_inactive_s": mean_i,
                "delta_s": delta,
            })
            if delta is None:
                continue
            floor = max(rel_threshold * mean_i, min_delta_s)
            if delta > floor and delta > best_delta:
                best_delta = delta
                straggler = r

        total_s = sum(d for d, _, _ in ticks)
        idle_weighted = sum(d * (pp - len(a)) for d, a, _ in ticks)
        bubble_measured = (idle_weighted / (pp * total_s)
                          if pp and total_s > 0 else None)
        bubble_analytic = None
        if pp > 0 and self._microbatches > 0:
            bubble_analytic = ((pp - 1)
                               / float(self._microbatches + pp - 1))

        comm = self._comm
        data_total = comm["hidden_s"] + comm["exposed_s"]
        axes = {
            "data": {
                "hidden_s": comm["hidden_s"],
                "exposed_s": comm["exposed_s"],
                "exposed_fraction": (comm["exposed_s"] / data_total
                                     if data_total > 0 else None),
                "buckets": comm["hidden_n"] + comm["exposed_n"],
            },
            "pipe": {
                "exposed_fraction": bubble_measured,
            },
        }
        return {
            "pp": pp,
            "microbatches": self._microbatches,
            "ticks": len(ticks),
            "per_stage": per_stage,
            "straggler": straggler,
            "straggler_delta_s": best_delta if straggler is not None
            else None,
            "bubble_fraction_measured": bubble_measured,
            "bubble_fraction_analytic": bubble_analytic,
            "comm_exposure": axes,
        }

    def reset(self):
        self._ticks.clear()
        self._pp = 0
        self._microbatches = 0
        self._comm = {"hidden_s": 0.0, "exposed_s": 0.0,
                      "hidden_n": 0, "exposed_n": 0}
