"""In-graph numerics: per-tensor / per-module-prefix gradient and
activation statistics, computed entirely inside the compiled step.

Why in-graph: mixed-precision training fails silently — an fp16/bf16
under/overflow shows up only as a tripped loss scaler or a NaN loss,
with no indication of WHICH tensor went bad (the entire rationale for
dynamic loss scaling in the reference's apex.amp). The resilience
guard (resilience/guard.py) detects a poisoned step and skips it, but
detection without attribution still kills the run blind when the skips
persist. T3 (PAPERS.md) makes the case that fine-grained tracking of
in-flight tensors must live inside the compiled program, not in host
callbacks; this module applies that to numerics:

- :func:`tensor_stats` — one compact, fixed pytree of f32 scalars per
  tensor (:class:`TensorStats`): l2 norm, absmax, rms, zero fraction,
  non-finite count, and fp16/bf16 under/overflow fractions against the
  formats' representable ranges. Pure ``jnp`` reductions — no host
  callback, ever (the tier-1 suite asserts ``"callback" not in`` the
  lowered HLO of a numerics-enabled step).
- :func:`tree_stats` — aggregates a grad/activation pytree into
  per-module-prefix groups (first ``prefix_depth`` components of each
  leaf path), so a gpt2-sized model yields ~tens of stat rows, not
  thousands. Group membership is resolved host-side at trace time; the
  values stay on device.

Norm/fraction stats are computed over the FINITE elements only (non-
finite values are masked to 0 before the reductions) so the trend
stays readable right through a blow-up — the poison signal is carried
by the ``nonfinite`` count, and the step that went bad still reports
the finite norms it had. An ``inf`` therefore counts as ``nonfinite``,
not as an fp16/bf16 overflow; the overflow fractions count *finite*
magnitudes beyond the target format's max.

Stats feed the :class:`~apex_tpu.telemetry.recorder.FlightRecorder`
ring buffer (the last-K-steps post-mortem story) and the opt-in
``numerics=`` knobs on ``DistributedDataParallel`` and the ZeRO
optimizers. Env knob: ``APEX_TPU_NUMERICS_DEPTH`` sets the default
grouping depth (default 2). See docs/observability.md ("Numerics").
"""

import os
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

ENV_DEPTH = "APEX_TPU_NUMERICS_DEPTH"
DEFAULT_PREFIX_DEPTH = 2

# Representable-range thresholds (jnp.finfo values, hard-coded so the
# thresholds are visible in reviews and never depend on backend float
# support): largest finite magnitude and smallest positive NORMAL.
FP16_MAX = 65504.0
FP16_TINY = 6.103515625e-05          # 2**-14
BF16_MAX = 3.3895313892515355e+38
BF16_TINY = 1.1754943508222875e-38   # 2**-126


class TensorStats(NamedTuple):
    """Fixed per-tensor/per-group stats pytree — nine f32 scalars, so a
    ring buffer of them is K*9 floats per group. Fractions are over the
    group's total element count; ``nonfinite`` is a count."""

    l2: jnp.ndarray                   # sqrt(sum of squares of finite elems)
    absmax: jnp.ndarray               # max |finite elem|
    rms: jnp.ndarray                  # sqrt(mean square of finite elems)
    zero_frac: jnp.ndarray            # fraction of exact (finite) zeros
    nonfinite: jnp.ndarray            # COUNT of NaN/Inf elements
    fp16_overflow_frac: jnp.ndarray   # finite |x| >  FP16_MAX
    fp16_underflow_frac: jnp.ndarray  # finite 0 < |x| < FP16_TINY
    bf16_overflow_frac: jnp.ndarray   # finite |x| >  BF16_MAX
    bf16_underflow_frac: jnp.ndarray  # finite 0 < |x| < BF16_TINY


STAT_FIELDS = TensorStats._fields


def default_prefix_depth() -> int:
    return int(os.environ.get(ENV_DEPTH, str(DEFAULT_PREFIX_DEPTH)))


def _raw_sums(x) -> Optional[Dict[str, Any]]:
    """Per-leaf partial sums (group-aggregatable: sums add, maxes max).
    None for non-inexact leaves — step counters can't be non-finite."""
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.inexact) or x.size == 0:
        return None
    xf = x.astype(jnp.float32)
    finite = jnp.isfinite(xf)
    a = jnp.abs(jnp.where(finite, xf, 0.0))
    f32 = jnp.float32
    return {
        "n": int(x.size),  # static — python int, never a tracer
        "sumsq": jnp.sum(a * a),
        "absmax": jnp.max(a),
        "zeros": jnp.sum(finite & (xf == 0)).astype(f32),
        "nonfinite": jnp.sum(~finite).astype(f32),
        "fp16_over": jnp.sum(a > FP16_MAX).astype(f32),
        "fp16_under": jnp.sum((a > 0) & (a < FP16_TINY)).astype(f32),
        "bf16_over": jnp.sum(a > BF16_MAX).astype(f32),
        "bf16_under": jnp.sum((a > 0) & (a < BF16_TINY)).astype(f32),
    }


def _finalize(acc) -> TensorStats:
    n = float(acc["n"])
    return TensorStats(
        l2=jnp.sqrt(acc["sumsq"]),
        absmax=acc["absmax"],
        rms=jnp.sqrt(acc["sumsq"] / n),
        zero_frac=acc["zeros"] / n,
        nonfinite=acc["nonfinite"],
        fp16_overflow_frac=acc["fp16_over"] / n,
        fp16_underflow_frac=acc["fp16_under"] / n,
        bf16_overflow_frac=acc["bf16_over"] / n,
        bf16_underflow_frac=acc["bf16_under"] / n,
    )


def tensor_stats(x) -> TensorStats:
    """:class:`TensorStats` of one array, fully in-graph (jit-safe, no
    host callback). Raises on non-float input — there is nothing to
    observe about an int tensor's dynamic range."""
    raw = _raw_sums(x)
    if raw is None:
        raise TypeError(
            f"tensor_stats: need a floating/complex array, got "
            f"{jnp.asarray(x).dtype}")
    return _finalize(raw)


def _leaf_path_str(path) -> str:
    # same formatting as parallel.distributed._leaf_path_str so prefix
    # groups line up with expert_param_predicate matching
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def group_prefix(path_str: str, prefix_depth: int) -> str:
    """First ``prefix_depth`` '/'-components of a leaf path — the
    module-prefix grouping key ("transformer/layer_3/attn/q_proj/w"
    at depth 2 -> "transformer/layer_3")."""
    parts = [p for p in path_str.split("/") if p]
    if not parts:
        return "<root>"
    return "/".join(parts[:max(1, int(prefix_depth))])


def tree_stats(tree, prefix_depth: Optional[int] = None, *,
               prefix: Optional[str] = None) -> Dict[str, TensorStats]:
    """Aggregate a pytree into ``{module_prefix: TensorStats}``.

    Grouping (leaf path -> first ``prefix_depth`` components) happens
    host-side at trace time; the per-group reductions are in-graph.
    Non-inexact leaves are skipped. ``prefix`` namespaces every key
    (``prefix="grads"`` -> ``"grads/<group>"``) so multiple stat sets —
    e.g. pre-compression gradients vs the dequantized synced gradients
    — can share one flat dict (and one flight-recorder ring).

    The result is a plain dict: a valid pytree with a FIXED structure
    for a fixed model, so it can ride through jit as carry state.
    """
    if prefix_depth is None:
        prefix_depth = default_prefix_depth()
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    groups: Dict[str, Dict[str, Any]] = {}
    for path, leaf in paths_leaves:
        raw = _raw_sums(leaf)
        if raw is None:
            continue
        key = group_prefix(_leaf_path_str(path), prefix_depth)
        if prefix:
            key = f"{prefix}/{key}"
        acc = groups.get(key)
        if acc is None:
            groups[key] = raw
        else:
            acc["n"] += raw["n"]
            acc["absmax"] = jnp.maximum(acc["absmax"], raw["absmax"])
            for k in ("sumsq", "zeros", "nonfinite", "fp16_over",
                      "fp16_under", "bf16_over", "bf16_under"):
                acc[k] = acc[k] + raw[k]
    return {k: _finalize(groups[k]) for k in sorted(groups)}


def stats_to_floats(stats) -> Dict[str, Dict[str, float]]:
    """Host-side: one ``jax.device_get`` of a ``{prefix: TensorStats}``
    dict into plain nested floats (JSON-ready)."""
    host = jax.device_get(stats)
    return {k: {f: float(getattr(v, f)) for f in STAT_FIELDS}
            for k, v in host.items()}


def first_nonfinite_prefix(stats_floats) -> Optional[str]:
    """First (sorted) module prefix whose non-finite count is > 0 in a
    host-side stats dict; None when everything is finite."""
    for k in sorted(stats_floats):
        if stats_floats[k].get("nonfinite", 0) > 0:
            return k
    return None
