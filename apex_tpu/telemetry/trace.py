"""Named spans with causal identity, device-sync fencing + profiler hooks.

A span measures host wall-clock (``time.perf_counter`` — monotonic; the
pipeline timers corrupted elapsed times under NTP skew with
``time.time``) between ``start()`` and ``stop()``, optionally fencing
outstanding device work on both edges so the interval matches device
time (the ``torch.cuda.synchronize`` analog). While open, a span nests
under ``jax.profiler.TraceAnnotation`` (host timeline) and
``jax.named_scope`` (HLO op names), so spans opened around traced code
show up in real profiler traces.

Causal identity: every span opened while the registry is enabled mints
a ``span_id`` and joins the ambient :class:`TraceContext` (a
contextvar), so nested spans form a tree under one ``trace_id`` — the
flight-recorder substrate ``tools/trace_export.py`` turns into a
Chrome/Perfetto trace. A span emits a ``span_begin`` event at open and
the (pre-existing) ``span`` event at close, both carrying
``trace_id``/``span_id``/``parent_id``. Host loops that multiplex many
logical requests (the serving scheduler) cannot scope a contextvar per
request; they stamp identities explicitly via :func:`emit_span` /
:func:`emit_flow`.

Spans are host-side only: nothing here inserts callbacks into compiled
programs, so a span wrapped around code *inside* ``jit`` measures trace
time (once per compilation) — by design, and the reason telemetry
disabled adds zero overhead to jitted step functions. Identity is part
of the same contract: a disabled registry mints no ids and never
touches the contextvar.

``start_profiler_trace()``/``stop_profiler_trace()`` bracket a real
``jax.profiler`` trace, gated by ``APEX_TPU_PROFILE_DIR`` so production
entry points can call them unconditionally.
"""

import contextlib
import contextvars
import dataclasses
import os
import time

from apex_tpu.telemetry.registry import get_registry

ENV_PROFILE_DIR = "APEX_TPU_PROFILE_DIR"


# -- causal identity --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Immutable causal identity: which trace this code runs under and
    which span is the current parent. ``baggage`` is a tuple of
    ``(key, value)`` pairs (kept a tuple so the dataclass stays frozen
    and cheap) propagated to children — request tier, replica label,
    anything a downstream span should inherit without plumbing."""

    trace_id: str
    span_id: str = ""
    parent_id: str = ""
    baggage: tuple = ()

    def bag(self):
        return dict(self.baggage)

    def to_wire(self):
        """JSON-serializable form for cross-process payloads (the
        fleet's KV-state migration carries this so donor + survivor
        spans stitch into one trace)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id,
                "baggage": [list(kv) for kv in self.baggage]}

    @classmethod
    def from_wire(cls, wire):
        return cls(trace_id=wire["trace_id"],
                   span_id=wire.get("span_id", ""),
                   parent_id=wire.get("parent_id", ""),
                   baggage=tuple((k, v) for k, v
                                 in wire.get("baggage", ())))


_CURRENT = contextvars.ContextVar("apex_tpu_trace_context", default=None)


def current_trace():
    """The ambient :class:`TraceContext`, or None outside any trace."""
    return _CURRENT.get()


def new_trace_id():
    return os.urandom(8).hex()


def new_span_id():
    return os.urandom(4).hex()


@contextlib.contextmanager
def trace_context(trace_id=None, *, baggage=None, registry=None):
    """Establish (or join) a trace for the dynamic extent of the block;
    spans opened inside parent under it. ``trace_id=None`` inherits the
    ambient trace or mints a fresh id at a root. Yields the installed
    context — or None with the contextvar untouched when telemetry is
    disabled (no ids are minted: the zero-overhead-off contract)."""
    reg = registry or get_registry()
    if not reg.enabled:
        yield None
        return
    parent = _CURRENT.get()
    if trace_id is None:
        trace_id = parent.trace_id if parent is not None else new_trace_id()
    merged = dict(parent.baggage) if parent is not None else {}
    if baggage:
        merged.update(baggage)
    ctx = TraceContext(
        trace_id=trace_id,
        span_id=parent.span_id if parent is not None else "",
        parent_id=parent.parent_id if parent is not None else "",
        baggage=tuple(sorted(merged.items())))
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


def emit_span(name, start, end=None, *, registry=None, trace_id=None,
              span_id=None, parent_id=None, **attrs):
    """Record an externally-timed span. ``start``/``end`` are raw
    ``time.perf_counter()`` readings (``end=None`` means now); the
    event's ``ts`` is the span END on the registry's epoch clock, so
    exporters recover the start as ``ts - duration_s``. Returns the
    span_id so callers can parent follow-up phases — None when
    telemetry is off, and nothing is recorded."""
    reg = registry or get_registry()
    if not reg.enabled:
        return None
    end = time.perf_counter() if end is None else end
    elapsed = end - start
    sid = span_id or new_span_id()
    reg.histogram(f"span/{name}").observe(elapsed)
    reg.event("span", name, duration_s=round(elapsed, 9),
              ts=round(reg.to_ts(end), 9), trace_id=trace_id,
              span_id=sid, parent_id=parent_id or "", **attrs)
    return sid


def emit_flow(name, flow_id, phase, *, registry=None, trace_id=None,
              **attrs):
    """Record one end of a cross-context arrow: ``phase="out"`` at the
    producer, ``"in"`` at the consumer. ``tools/trace_export.py`` pairs
    out/in records sharing ``flow_id`` into Chrome flow events (the
    arrows across process rows at a migration handoff)."""
    reg = registry or get_registry()
    if not reg.enabled:
        return
    reg.event("trace_flow", name, flow_id=flow_id, phase=phase,
              trace_id=trace_id, **attrs)


def device_sync():
    """Fence outstanding device work (best-effort; the TPU analog of
    ``torch.cuda.synchronize``)."""
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass


def _annotations(name):
    """TraceAnnotation + named_scope, each best-effort (profiling
    support can be absent on exotic backends)."""
    stack = contextlib.ExitStack()
    try:
        import jax

        try:
            stack.enter_context(jax.profiler.TraceAnnotation(name))
        except Exception:
            pass
        try:
            stack.enter_context(jax.named_scope(
                name.replace("/", "_").replace(" ", "_")))
        except Exception:
            pass
    except Exception:
        pass
    return stack


class Span:
    """Restartable named timer; also usable as a context manager.

    ``sync=True`` fences the device on both edges. Timing always works
    (``_timers.py`` shims onto this even with telemetry off); metric
    recording — a ``span/<name>`` histogram in seconds, a
    ``span_begin`` event at open, and a ``span`` event at close, the
    events carrying ``trace_id``/``span_id``/``parent_id`` from the
    ambient :class:`TraceContext` — happens only when the registry is
    enabled. While open (and enabled) the span installs itself as the
    current context, so nested spans parent under it.
    """

    __slots__ = ("name", "sync", "attrs", "start_time", "_stack",
                 "_registry", "trace_id", "span_id", "parent_id",
                 "_token")

    def __init__(self, name, *, sync=False, registry=None, **attrs):
        self.name = name
        self.sync = sync
        self.attrs = attrs
        self.start_time = None
        self._stack = None
        self._registry = registry
        self.trace_id = None
        self.span_id = None
        self.parent_id = None
        self._token = None

    def start(self):
        if self.sync:
            device_sync()
        reg = self._registry or get_registry()
        if reg.enabled:
            ctx = _CURRENT.get()
            self.trace_id = (ctx.trace_id if ctx is not None
                             else new_trace_id())
            self.parent_id = ctx.span_id if ctx is not None else ""
            self.span_id = new_span_id()
            self._token = _CURRENT.set(TraceContext(
                trace_id=self.trace_id, span_id=self.span_id,
                parent_id=self.parent_id,
                baggage=ctx.baggage if ctx is not None else ()))
            reg.event("span_begin", self.name, trace_id=self.trace_id,
                      span_id=self.span_id, parent_id=self.parent_id,
                      **self.attrs)
        self._stack = _annotations(self.name)
        self.start_time = time.perf_counter()
        return self

    def stop(self):
        """Close the span; returns the elapsed seconds."""
        if self.sync:
            device_sync()
        elapsed = time.perf_counter() - self.start_time
        if self._stack is not None:
            self._stack.close()
            self._stack = None
        if self._token is not None:
            # Reset can only happen from the context that set the
            # token; a span handed across threads keeps its identity
            # but cannot pop the foreign context.
            with contextlib.suppress(ValueError):
                _CURRENT.reset(self._token)
            self._token = None
        reg = self._registry or get_registry()
        if reg.enabled:
            reg.histogram(f"span/{self.name}").observe(elapsed)
            ids = {}
            if self.span_id is not None:
                ids = {"trace_id": self.trace_id,
                       "span_id": self.span_id,
                       "parent_id": self.parent_id}
            reg.event("span", self.name, duration_s=round(elapsed, 9),
                      **ids, **self.attrs)
        return elapsed

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def span(name, *, sync=False, registry=None, **attrs):
    """``with span("train/step"): ...`` — see :class:`Span`."""
    return Span(name, sync=sync, registry=registry, **attrs)


_PROFILER_ACTIVE = False


def start_profiler_trace(logdir=None):
    """Start a ``jax.profiler`` trace when ``APEX_TPU_PROFILE_DIR`` (or
    ``logdir``) names a directory; returns True iff a trace started.
    Safe to call unconditionally and when a trace is already running."""
    global _PROFILER_ACTIVE
    logdir = logdir or os.environ.get(ENV_PROFILE_DIR)
    if not logdir or _PROFILER_ACTIVE:
        return False
    try:
        import jax

        jax.profiler.start_trace(logdir)
    except Exception:
        return False
    _PROFILER_ACTIVE = True
    reg = get_registry()
    if reg.enabled:
        reg.event("profiler", "start", logdir=logdir)
    return True


def stop_profiler_trace():
    """Stop the trace started by :func:`start_profiler_trace`; returns
    True iff one was stopped."""
    global _PROFILER_ACTIVE
    if not _PROFILER_ACTIVE:
        return False
    _PROFILER_ACTIVE = False
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        return False
    reg = get_registry()
    if reg.enabled:
        reg.event("profiler", "stop")
    return True
