"""Named spans with optional device-sync fencing + profiler hooks.

A span measures host wall-clock (``time.perf_counter`` — monotonic; the
pipeline timers corrupted elapsed times under NTP skew with
``time.time``) between ``start()`` and ``stop()``, optionally fencing
outstanding device work on both edges so the interval matches device
time (the ``torch.cuda.synchronize`` analog). While open, a span nests
under ``jax.profiler.TraceAnnotation`` (host timeline) and
``jax.named_scope`` (HLO op names), so spans opened around traced code
show up in real profiler traces.

Spans are host-side only: nothing here inserts callbacks into compiled
programs, so a span wrapped around code *inside* ``jit`` measures trace
time (once per compilation) — by design, and the reason telemetry
disabled adds zero overhead to jitted step functions.

``start_profiler_trace()``/``stop_profiler_trace()`` bracket a real
``jax.profiler`` trace, gated by ``APEX_TPU_PROFILE_DIR`` so production
entry points can call them unconditionally.
"""

import contextlib
import os
import time

from apex_tpu.telemetry.registry import get_registry

ENV_PROFILE_DIR = "APEX_TPU_PROFILE_DIR"


def device_sync():
    """Fence outstanding device work (best-effort; the TPU analog of
    ``torch.cuda.synchronize``)."""
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass


def _annotations(name):
    """TraceAnnotation + named_scope, each best-effort (profiling
    support can be absent on exotic backends)."""
    stack = contextlib.ExitStack()
    try:
        import jax

        try:
            stack.enter_context(jax.profiler.TraceAnnotation(name))
        except Exception:
            pass
        try:
            stack.enter_context(jax.named_scope(
                name.replace("/", "_").replace(" ", "_")))
        except Exception:
            pass
    except Exception:
        pass
    return stack


class Span:
    """Restartable named timer; also usable as a context manager.

    ``sync=True`` fences the device on both edges. Timing always works
    (``_timers.py`` shims onto this even with telemetry off); metric
    recording — a ``span/<name>`` histogram in seconds plus a ``span``
    event — happens only when the registry is enabled.
    """

    __slots__ = ("name", "sync", "attrs", "start_time", "_stack",
                 "_registry")

    def __init__(self, name, *, sync=False, registry=None, **attrs):
        self.name = name
        self.sync = sync
        self.attrs = attrs
        self.start_time = None
        self._stack = None
        self._registry = registry

    def start(self):
        if self.sync:
            device_sync()
        self._stack = _annotations(self.name)
        self.start_time = time.perf_counter()
        return self

    def stop(self):
        """Close the span; returns the elapsed seconds."""
        if self.sync:
            device_sync()
        elapsed = time.perf_counter() - self.start_time
        if self._stack is not None:
            self._stack.close()
            self._stack = None
        reg = self._registry or get_registry()
        if reg.enabled:
            reg.histogram(f"span/{self.name}").observe(elapsed)
            reg.event("span", self.name, duration_s=round(elapsed, 9),
                      **self.attrs)
        return elapsed

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def span(name, *, sync=False, registry=None, **attrs):
    """``with span("train/step"): ...`` — see :class:`Span`."""
    return Span(name, sync=sync, registry=registry, **attrs)


_PROFILER_ACTIVE = False


def start_profiler_trace(logdir=None):
    """Start a ``jax.profiler`` trace when ``APEX_TPU_PROFILE_DIR`` (or
    ``logdir``) names a directory; returns True iff a trace started.
    Safe to call unconditionally and when a trace is already running."""
    global _PROFILER_ACTIVE
    logdir = logdir or os.environ.get(ENV_PROFILE_DIR)
    if not logdir or _PROFILER_ACTIVE:
        return False
    try:
        import jax

        jax.profiler.start_trace(logdir)
    except Exception:
        return False
    _PROFILER_ACTIVE = True
    reg = get_registry()
    if reg.enabled:
        reg.event("profiler", "start", logdir=logdir)
    return True


def stop_profiler_trace():
    """Stop the trace started by :func:`start_profiler_trace`; returns
    True iff one was stopped."""
    global _PROFILER_ACTIVE
    if not _PROFILER_ACTIVE:
        return False
    _PROFILER_ACTIVE = False
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        return False
    reg = get_registry()
    if reg.enabled:
        reg.event("profiler", "stop")
    return True
