"""Process-wide metrics registry with a JSONL event sink.

The observability substrate for apex_tpu: counters (monotonic),
gauges (last value), and histograms (count/total/min/max/last), plus a
structured event stream written as JSON Lines under
``$APEX_TPU_TELEMETRY_DIR``. Everything is **host-side**: recording
happens in Python (at trace time for code inside ``jit`` — once per
compilation, which is exactly the per-step accounting for a compiled
step function) and never inserts callbacks into compiled programs.

Disabled is the default and costs nothing: ``get_registry()`` resolves
to a registry whose ``enabled`` flag is False unless
``APEX_TPU_TELEMETRY_DIR`` is set (or ``enable()`` is called
programmatically — ``bench.py`` does this to collect in-memory comm
accounting even when no sink directory is configured), and every
``counter()/gauge()/histogram()`` call on a disabled registry returns a
shared no-op instrument.

Rank discipline: on multi-process runs each process writes its own
``telemetry-rank<N>.jsonl``; ``APEX_TPU_TELEMETRY_RANK0_ONLY=1``
restricts both the sink and the ``log_summary`` logging path (built on
:mod:`apex_tpu._logging`'s rank-aware formatter) to process 0.

Clock discipline: every event carries two stamps — ``t`` (wall clock,
human-facing, NTP-skewable) and ``ts`` (seconds on one monotonic
``perf_counter`` epoch per registry). A ``trace_epoch`` header record
written at sink open carries ``epoch_unix`` (the wall-clock value of
``ts == 0``), so ``tools/trace_export.py`` can place every rank's
monotonic timeline on one absolute axis without trusting per-event
wall clocks to agree across processes.
"""

import collections
import json
import os
import threading
import time

ENV_DIR = "APEX_TPU_TELEMETRY_DIR"
ENV_RANK0_ONLY = "APEX_TPU_TELEMETRY_RANK0_ONLY"


def _process_index():
    """Best-effort process index; 0 when jax is absent/uninitialized.
    (Same resolution order as ``_logging._get_rank_info`` — the jax
    fallback — but kept independent so the registry never forces a
    backend bring-up.)"""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class Counter:
    """Monotonic float counter. ``inc`` only; use a Gauge for levels."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        with self._lock:
            self.value += float(amount)

    def read(self):
        """Locked read — pairs with :meth:`inc` so a snapshot never
        observes a torn update."""
        with self._lock:
            return self.value


class Gauge:
    """Last-value-wins instrument."""

    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = None

    def set(self, value):
        self.value = float(value)


class Histogram:
    """Streaming summary (count/total/min/max/last) plus a bounded
    sample reservoir for tail percentiles.

    The reservoir keeps the most recent ``RESERVOIR`` observations (a
    sliding window, not a statistical sample — serving latency wants
    the RECENT tail, and p99-of-the-last-4096 answers "how is the
    system doing now"); :meth:`percentile` and the ``p50``/``p99``
    summary fields read it. Older aggregate fields are exact over the
    full stream."""

    RESERVOIR = 4096

    __slots__ = ("name", "count", "total", "min", "max", "last",
                 "_samples", "_lock")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.last = None
        self._samples = collections.deque(maxlen=self.RESERVOIR)
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self.last = value
            self._samples.append(value)

    @staticmethod
    def _interp(samples, q):
        if not samples:
            return None
        if len(samples) == 1:
            return samples[0]
        pos = (len(samples) - 1) * (float(q) / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(samples) - 1)
        return samples[lo] + (samples[hi] - samples[lo]) * (pos - lo)

    def percentile(self, q):
        """Linear-interpolated percentile (``q`` in [0, 100]) over the
        reservoir window; None before the first observation."""
        with self._lock:
            samples = sorted(self._samples)
        return self._interp(samples, q)

    def summary(self):
        """Consistent summary: every field — aggregates AND the
        percentile pair — is read under ONE lock acquisition, so
        ``count`` always agrees with the reservoir it was taken with
        (the torn read the old piecemeal version allowed)."""
        with self._lock:
            count, total = self.count, self.total
            mn, mx, last = self.min, self.max, self.last
            samples = sorted(self._samples)
        return {
            "count": count,
            "total": total,
            "mean": (total / count) if count else None,
            "min": mn,
            "max": mx,
            "last": last,
            "p50": self._interp(samples, 50),
            "p99": self._interp(samples, 99),
        }


class _Null:
    """Shared no-op instrument handed out by a disabled registry — the
    zero-overhead-off contract: call sites never branch on enablement."""

    __slots__ = ()
    value = 0.0
    count = 0

    def inc(self, amount=1.0):
        pass

    def set(self, value):
        pass

    def observe(self, value):
        pass

    def percentile(self, q):
        return None


_NULL = _Null()


class MetricsRegistry:
    """Counters + gauges + histograms + a JSONL event sink.

    ``enabled`` gates *everything*; a disabled registry returns no-op
    instruments and drops events, so library code records
    unconditionally and pays nothing by default.
    """

    def __init__(self, *, enabled=False, jsonl_dir=None, rank0_only=None):
        self._lock = threading.RLock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}
        self._enabled = bool(enabled) or jsonl_dir is not None
        self._jsonl_dir = jsonl_dir
        self._sink = None
        self._rank0_only = (os.environ.get(ENV_RANK0_ONLY) == "1"
                            if rank0_only is None else bool(rank0_only))
        # In-process event taps (the live monitor's intake). A tuple so
        # event() can iterate a consistent view without holding the
        # lock; mutation replaces the tuple under the lock.
        self._taps = ()
        # Sampled back-to-back so epoch_unix ~= the wall clock at ts=0;
        # residual skew is one statement, not an NTP step.
        self._perf_origin = time.perf_counter()
        self._epoch_unix = time.time()

    # -- clock --------------------------------------------------------------

    def now(self):
        """Seconds since this registry's ``perf_counter`` epoch — the
        monotonic clock every event ``ts`` shares."""
        return time.perf_counter() - self._perf_origin

    def to_ts(self, perf_t):
        """Convert a raw ``time.perf_counter()`` reading (e.g. a span
        start captured before the event is emitted) onto the ``ts``
        clock."""
        return perf_t - self._perf_origin

    # -- enablement ---------------------------------------------------------

    @property
    def enabled(self):
        return self._enabled

    @property
    def jsonl_dir(self):
        return self._jsonl_dir

    def enable(self, jsonl_dir=None):
        """Turn collection on; idempotent. ``jsonl_dir`` (may be None
        for in-memory-only collection) attaches/retargets the event
        sink."""
        with self._lock:
            self._enabled = True
            if jsonl_dir and jsonl_dir != self._jsonl_dir:
                self._close_sink_locked()
                self._jsonl_dir = jsonl_dir
        return self

    def disable(self):
        with self._lock:
            self._enabled = False
            self._close_sink_locked()
        return self

    # -- instruments --------------------------------------------------------

    def counter(self, name):
        if not self._enabled:
            return _NULL
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name):
        if not self._enabled:
            return _NULL
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name):
        if not self._enabled:
            return _NULL
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def counter_value(self, name):
        """Current value of a counter (0.0 when absent/disabled) —
        the delta-measurement hook bench.py uses."""
        with self._lock:
            c = self._counters.get(name)
            return c.value if c is not None else 0.0

    # -- events -------------------------------------------------------------

    def add_event_tap(self, fn):
        """Register an in-process event consumer: ``fn(record)`` is
        called synchronously for every event an *enabled* registry
        emits, before (and regardless of) the JSONL write — the live
        monitor's intake. Taps see events even when no sink dir is
        configured and are NOT gated by rank0-only mode (that gates the
        on-disk/logging paths; a tap is this process watching itself).
        Taps must treat the record as read-only. Returns ``fn``."""
        with self._lock:
            if fn not in self._taps:
                self._taps = self._taps + (fn,)
        return fn

    def remove_event_tap(self, fn):
        with self._lock:
            self._taps = tuple(t for t in self._taps if t is not fn)

    def event(self, kind, name, **fields):
        """Dispatch one structured event: to every registered tap, and
        appended to the JSONL sink (when a sink dir is configured and
        this rank may write). No-op — and no record is even built —
        unless enabled and at least one consumer exists."""
        if not self._enabled:
            return
        taps = self._taps
        sink_ok = (self._jsonl_dir is not None
                   and not (self._rank0_only and _process_index() != 0))
        if not taps and not sink_ok:
            return
        rec = {"t": round(time.time(), 6), "ts": round(self.now(), 9),
               "kind": kind, "name": name}
        rec.update(fields)  # an explicit ts= overrides the stamp
        for tap in taps:
            try:
                tap(rec)
            except Exception:
                pass  # a broken monitor must never take down training
        if not sink_ok:
            return
        line = json.dumps(rec, default=str)
        with self._lock:
            sink = self._open_sink_locked()
            if sink is not None:
                sink.write(line + "\n")
                sink.flush()

    def _open_sink_locked(self):
        if self._sink is None and self._jsonl_dir is not None:
            try:
                os.makedirs(self._jsonl_dir, exist_ok=True)
                path = os.path.join(
                    self._jsonl_dir,
                    f"telemetry-rank{_process_index()}.jsonl")
                self._sink = open(path, "a")
                # Clock-alignment header: epoch_unix is the wall clock
                # at ts=0 for everything this registry writes below it.
                header = {
                    "t": round(time.time(), 6),
                    "ts": round(self.now(), 9),
                    "kind": "trace_epoch", "name": "epoch",
                    "epoch_unix": round(time.time() - self.now(), 6),
                    "pid": os.getpid(),
                    "rank": _process_index(),
                }
                self._sink.write(json.dumps(header) + "\n")
                self._sink.flush()
            except OSError:
                # an unwritable sink dir must never take down training
                self._jsonl_dir = None
                self._sink = None
        return self._sink

    def _close_sink_locked(self):
        if self._sink is not None:
            try:
                self._sink.close()
            finally:
                self._sink = None

    # -- snapshots ----------------------------------------------------------

    def snapshot(self):
        """Atomic plain-dict view of every instrument
        (JSON-serializable) — the monitor's read primitive.

        Consistency contract: the instrument *set* is frozen under the
        registry lock (no instrument appears or vanishes mid-walk), and
        each instrument is then read through its own locked read path
        (``Counter.read``, ``Histogram.summary`` — one lock acquisition
        per instrument, so no summary is ever internally torn between
        its aggregate fields and its percentile reservoir). ``ts`` is
        the registry-monotonic stamp of the snapshot itself, so two
        snapshots bound a well-defined rate window."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            histograms = list(self._histograms.items())
            ts = self.now()
        return {
            "ts": round(ts, 9),
            "counters": {k: c.read() for k, c in counters},
            "gauges": {k: g.value for k, g in gauges},
            "histograms": {k: h.summary() for k, h in histograms},
        }

    def flush(self):
        """Write one ``kind="summary"`` event carrying the full
        snapshot — the record tools/telemetry_report.py aggregates."""
        self.event("summary", "registry", **self.snapshot())

    def reset(self):
        """Drop all instruments (tests / per-phase accounting)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    def log_summary(self, logger=None, level=None):
        """Emit a one-line snapshot through the rank-aware logging path
        (``_logging.RankInfoFormatter`` provides ``%(rank_info)s``).
        Honors rank0-only mode."""
        import logging

        from apex_tpu.transformer.log_util import get_transformer_logger

        if not self._enabled:
            return
        if self._rank0_only and _process_index() != 0:
            return
        logger = logger or get_transformer_logger("apex_tpu.telemetry")
        logger.log(level or logging.INFO,
                   "telemetry %s", json.dumps(self.snapshot()))


_REGISTRY = None
_REGISTRY_LOCK = threading.Lock()


def get_registry():
    """The process-wide registry, created on first use. Enabled (with
    the JSONL sink attached) iff ``APEX_TPU_TELEMETRY_DIR`` was set when
    first resolved; call ``get_registry().enable(...)`` to opt in
    programmatically afterwards."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry(
                    jsonl_dir=os.environ.get(ENV_DIR) or None)
    return _REGISTRY


def set_registry(registry):
    """Swap the process-wide registry (tests); returns the previous one."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        prev, _REGISTRY = _REGISTRY, registry
    return prev


class use_registry:
    """Context manager installing ``registry`` as process-wide for the
    block — the test idiom for isolated measurement::

        with use_registry(MetricsRegistry(enabled=True)) as reg:
            ...
            assert reg.counter_value("comm/bytes") > 0
    """

    def __init__(self, registry):
        self.registry = registry
        self._prev = None

    def __enter__(self):
        self._prev = set_registry(self.registry)
        return self.registry

    def __exit__(self, *exc):
        set_registry(self._prev)
        return False
