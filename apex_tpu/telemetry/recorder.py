"""Step flight recorder: a device-side ring buffer of per-layer stats.

The post-mortem problem: when ``check_guard`` escalates to
``NonFiniteError`` after K consecutive skipped steps, the run dies with
no record of which layer went bad or how its norms trended into the
blow-up — per-step host transfers of stats would answer it, but at the
cost of a device->host sync every step, which is exactly what the
jit-native guard exists to avoid.

The :class:`FlightRecorder` answer: keep the last K steps of
:func:`~apex_tpu.telemetry.numerics.tree_stats` resident ON DEVICE as a
stacked ring buffer threaded through the step as carry state (donate it
with the optimizer state). :meth:`record` is one dynamic-update-slice
per stat leaf at ``cursor % K`` — no host callback, no transfer, one
small fixed buffer (K x 9 floats per module prefix). The host fetches
the ring exactly once, when something already went wrong:
:meth:`dump_postmortem` writes ``numerics-postmortem-rank<N>.json``
naming the first module prefix whose non-finite count is > 0, with the
prior steps' (finite) stat trend alongside — the "which layer, which
step, how did it trend" answer the guard escalation was missing.

Recording is UNCONDITIONAL by design: ``guarded_update`` records the
step's stats outside its ``jnp.where`` revert, so the ring contents
after a skipped step are bit-identical to the committed case — the
poisoned step's stats are precisely the evidence the post-mortem
exists to capture, and must never be reverted away with the state.

Env knobs: ``APEX_TPU_NUMERICS_RING`` (ring length, default 8),
``APEX_TPU_NUMERICS_DIR`` (post-mortem directory; falls back to the
telemetry JSONL dir, then the CWD). See docs/observability.md.
"""

import json
import os
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.telemetry import numerics
from apex_tpu.telemetry.registry import _process_index, get_registry

ENV_RING = "APEX_TPU_NUMERICS_RING"
ENV_DIR = "APEX_TPU_NUMERICS_DIR"
DEFAULT_RING_LENGTH = 8
POSTMORTEM_BASENAME = "numerics-postmortem-rank{rank}.json"


def default_ring_length() -> int:
    return int(os.environ.get(ENV_RING, str(DEFAULT_RING_LENGTH)))


class RecorderState(NamedTuple):
    """The device-resident ring (a pytree — donate it through the jitted
    step like optimizer state)."""

    buffer: Any           # {prefix: TensorStats of (K,) f32 arrays}
    steps: jnp.ndarray    # (K,) i32 step numbers; -1 = never written
    cursor: jnp.ndarray   # () i32: lifetime records (next slot = cursor % K)


class FlightRecorder:
    """Ring-buffer policy object (host-side; the state is the pytree).

    ``length`` is the ring capacity K (default
    ``$APEX_TPU_NUMERICS_RING`` or 8); ``prefix_depth`` is the
    module-prefix grouping depth used when ``guarded_update`` derives
    stats itself (default ``$APEX_TPU_NUMERICS_DEPTH`` or 2).
    """

    def __init__(self, length: Optional[int] = None,
                 prefix_depth: Optional[int] = None):
        self.length = default_ring_length() if length is None else int(length)
        if self.length < 1:
            raise ValueError(f"FlightRecorder: length must be >= 1, "
                             f"got {self.length}")
        self.prefix_depth = (numerics.default_prefix_depth()
                             if prefix_depth is None else int(prefix_depth))
        # set by dump_postmortem — lets callers (bench, smoke stages)
        # find the record check_guard dumped on their behalf
        self.last_postmortem = None

    # -- state ----------------------------------------------------------

    def init_state(self, tree, prefixes=None) -> RecorderState:
        """Zeroed ring shaped for ``tree`` — either the grads/params
        pytree the step will record stats of, or an already-computed
        ``{prefix: TensorStats}`` dict (e.g. traced once via
        ``jax.eval_shape`` around the DDP sync). ``prefixes`` mirrors
        the namespacing the step will record — pass
        ``("grads", "synced")`` when feeding the ring from
        ``DistributedDataParallel(numerics=...)``'s stats. Uses
        ``jax.eval_shape`` so init costs no compute and is
        trace-safe."""
        if isinstance(tree, dict) and tree and all(
                isinstance(v, numerics.TensorStats) for v in tree.values()):
            shapes = jax.eval_shape(lambda t: t, tree)
        else:
            def build(t):
                if not prefixes:
                    return numerics.tree_stats(
                        t, prefix_depth=self.prefix_depth)
                out = {}
                for pre in prefixes:
                    out.update(numerics.tree_stats(
                        t, prefix_depth=self.prefix_depth, prefix=pre))
                return out

            shapes = jax.eval_shape(build, tree)
        buffer = jax.tree_util.tree_map(
            lambda s: jnp.zeros((self.length,), s.dtype), shapes)
        return RecorderState(
            buffer=buffer,
            steps=jnp.full((self.length,), -1, jnp.int32),
            cursor=jnp.zeros((), jnp.int32),
        )

    def record(self, state: RecorderState, step, stats) -> RecorderState:
        """Write one step's ``{prefix: TensorStats}`` into the ring slot
        ``cursor % K`` (evicting the oldest entry once full) and advance
        the cursor. Pure in-graph: one dynamic-update-slice per stat
        leaf, no host callback — safe inside jit/shard_map."""
        idx = state.cursor % self.length
        buffer = jax.tree_util.tree_map(
            lambda buf, s: buf.at[idx].set(jnp.asarray(s, buf.dtype)),
            state.buffer, stats)
        return RecorderState(
            buffer=buffer,
            steps=state.steps.at[idx].set(
                jnp.asarray(step, jnp.int32)),
            cursor=state.cursor + 1,
        )

    # -- host side ------------------------------------------------------

    def fetch(self, state: RecorderState):
        """ONE device->host transfer of the whole ring, unrolled into
        rows oldest -> newest: ``[{"step": int, "stats": {prefix:
        {field: float}}}, ...]`` (at most K rows; fewer before the ring
        fills)."""
        host = jax.device_get(state)
        cursor = int(host.cursor)
        count = min(cursor, self.length)
        rows = []
        for j in range(count):
            i = (cursor - count + j) % self.length
            rows.append({
                "step": int(host.steps[i]),
                "stats": {
                    prefix: {f: float(getattr(ts, f)[i])
                             for f in numerics.STAT_FIELDS}
                    for prefix, ts in host.buffer.items()},
            })
        return rows

    @staticmethod
    def first_nonfinite(rows):
        """Scan rows oldest -> newest for the first module prefix whose
        non-finite count is > 0; returns ``(step, prefix)`` or
        ``(None, None)`` when the whole ring is finite."""
        for row in rows:
            prefix = numerics.first_nonfinite_prefix(row["stats"])
            if prefix is not None:
                return row["step"], prefix
        return None, None

    def resolve_dir(self, directory=None, registry=None):
        if directory:
            return directory
        env = os.environ.get(ENV_DIR)
        if env:
            return env
        reg = registry or get_registry()
        return reg.jsonl_dir or "."

    def dump_postmortem(self, state: RecorderState, directory=None, *,
                        reason="guard_skip", registry=None, extra=None):
        """Fetch the ring once and write
        ``numerics-postmortem-rank<N>.json`` (atomic tmp+rename;
        overwrites — the newest wreckage is the one that matters).
        Returns the record dict (with ``"path"``) and remembers it as
        ``self.last_postmortem``; also lands a ``numerics`` event in
        the registry when enabled."""
        rows = self.fetch(state)
        step, prefix = self.first_nonfinite(rows)
        rank = _process_index()
        directory = self.resolve_dir(directory, registry)
        record = {
            "t": round(time.time(), 6),
            "reason": reason,
            "rank": rank,
            "ring_length": self.length,
            "prefix_depth": self.prefix_depth,
            "first_nonfinite_step": step,
            "first_nonfinite_prefix": prefix,
            "rows": rows,
        }
        if extra:
            record.update(extra)
        path = os.path.join(directory,
                            POSTMORTEM_BASENAME.format(rank=rank))
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1)
            os.replace(tmp, path)
            record["path"] = path
        except OSError:
            # an unwritable post-mortem dir must never mask the
            # escalation that triggered the dump
            record["path"] = None
        reg = registry or get_registry()
        if reg.enabled:
            reg.event("numerics", "postmortem", reason=reason,
                      path=record["path"], rows=len(rows),
                      first_nonfinite_step=step,
                      first_nonfinite_prefix=prefix)
        self.last_postmortem = record
        return record
