"""HBM budget accounting: know the peak before the chip finds out.

The other silent killer next to recompilation: a config that exceeds
HBM dies with a raw ``RESOURCE_EXHAUSTED`` naming no buffer, usually
tens of minutes into a compile — and the ROADMAP's "as fast as the
hardware allows" (raise the batch, drop remat, widen the model) is
exactly the knob-set you cannot touch safely without knowing peak HBM
headroom per step. Memory attribution is also what makes ZeRO-style
sharding decisions tractable (Xu et al., arXiv:2004.13336): the win IS
bytes, so the bytes must be measurable.

Four host-side pieces (nothing here touches the traced program):

- :func:`step_memory` — wraps ``lowered.compile().memory_analysis()``
  into one report dict: argument / output / temp / generated-code
  bytes, the derived ``peak_bytes``, the backend's HBM capacity
  (per-backend table, ``APEX_TPU_HBM_GB`` override, or the device's own
  ``memory_stats()['bytes_limit']`` when it reports one) and the
  ``headroom_frac`` that lands in the ``memory/hbm_headroom`` gauge.
  Every report is appended to an in-process headroom trend ring — the
  post-mortem's "how did it trend" answer.
- :func:`live_buffer_census` — groups ``jax.live_arrays()`` by
  shape/dtype (plus caller-supplied pytree labels, e.g.
  ``labels={"params": params, "opt": opt_state}`` — live arrays carry
  no named scopes, so attribution comes from matching the caller's own
  trees) into a top-K table by bytes.
- :func:`preflight` — compare estimated peak against capacity *before*
  dispatch: warn, or raise :class:`MemoryBudgetError` with
  ``strict=True``.
- :func:`oom_postmortem` / :func:`oom_guard` — catch
  ``RESOURCE_EXHAUSTED`` from a guarded train step and write an atomic
  ``memory-postmortem-rank<N>.json`` (census + last step_memory report
  + headroom trend), mirroring the numerics post-mortem format, then
  re-raise as :class:`HBMExhaustedError`. ``resilience.guarded_call``
  is the train-loop entry point; ``faults.inject_alloc_failure`` makes
  the path testable on CPU.

Env knobs: ``APEX_TPU_HBM_GB`` (capacity override, in GB),
``APEX_TPU_MEMORY_DIR`` (post-mortem directory; falls back to the
telemetry JSONL dir, then the CWD). See docs/observability.md.
"""

import collections
import contextlib
import json
import os
import time
import warnings

from apex_tpu.telemetry.registry import _process_index, get_registry

ENV_HBM_GB = "APEX_TPU_HBM_GB"
ENV_DIR = "APEX_TPU_MEMORY_DIR"
POSTMORTEM_BASENAME = "memory-postmortem-rank{rank}.json"
TREND_LENGTH = 64

# Per-backend HBM capacity defaults, bytes. Heuristic stand-ins — chip
# generations differ (TPU v4 32G, v5e 16G, v5p 95G) and the CPU "HBM"
# is host RAM; the authoritative sources are, in order,
# $APEX_TPU_HBM_GB and the device's own memory_stats()['bytes_limit'].
_HBM_DEFAULTS_BYTES = {
    "tpu": int(32e9),
    "gpu": int(80e9),
    "cpu": int(16e9),
}


class MemoryBudgetError(RuntimeError):
    """Raised by ``preflight(strict=True)`` when the estimated peak
    exceeds HBM capacity — fail before dispatch, not 20 minutes into
    the compile."""


class HBMExhaustedError(RuntimeError):
    """Raised by :func:`oom_guard` after a RESOURCE_EXHAUSTED killed a
    step and the memory post-mortem landed — the OOM sibling of
    ``resilience.NonFiniteError``."""


def _default_backend():
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "cpu"


def _device_bytes_limit():
    """The accelerator's own reported capacity, when it reports one
    (real TPUs do via ``Device.memory_stats()``; CPU returns None)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        return int(limit) if limit else None
    except Exception:
        return None


def hbm_capacity_bytes(backend=None):
    """HBM capacity in bytes for ``backend`` (default: the current jax
    default backend). Resolution order: ``$APEX_TPU_HBM_GB`` (decimal
    GB) > the device's measured ``bytes_limit`` > the per-backend
    default table."""
    env = os.environ.get(ENV_HBM_GB)
    if env:
        return int(float(env) * 1e9)
    measured = _device_bytes_limit()
    if measured:
        return measured
    if backend is None:
        backend = _default_backend()
    return _HBM_DEFAULTS_BYTES.get(backend, _HBM_DEFAULTS_BYTES["tpu"])


# -- step memory accounting -------------------------------------------------

# last report + bounded headroom trend, fed by report_from_lowered and
# consumed by the OOM post-mortem ("what did headroom look like before
# the step died")
_LAST_REPORT = None
_TREND = collections.deque(maxlen=TREND_LENGTH)


def headroom_trend():
    """The last ``TREND_LENGTH`` step-memory snapshots, oldest first:
    ``[{"t", "peak_bytes", "headroom_frac"}, ...]``."""
    return list(_TREND)


def reset_trend():
    """Drop the trend + last report (test isolation)."""
    global _LAST_REPORT
    _LAST_REPORT = None
    _TREND.clear()


def report_from_lowered(lowered, *, backend=None, registry=None,
                        record=True, name="step"):
    """Memory report for an already-``.lower()``-ed computation.

    Compiles it (``lowered.compile()`` — with the persistent compile
    cache enabled this is a disk hit when the same program was compiled
    before; without it, one extra compile) and reads XLA's own
    ``memory_analysis()``. Returns None when the backend offers no
    analysis. The report lands in the ``memory/hbm_headroom`` /
    ``memory/peak_hbm_bytes`` gauges, a ``memory`` JSONL event, and the
    in-process headroom trend unless ``record=False``."""
    global _LAST_REPORT
    try:
        stats = lowered.compile().memory_analysis()
    except Exception:
        return None
    if stats is None:
        return None
    arg = int(getattr(stats, "argument_size_in_bytes", 0))
    out = int(getattr(stats, "output_size_in_bytes", 0))
    temp = int(getattr(stats, "temp_size_in_bytes", 0))
    code = int(getattr(stats, "generated_code_size_in_bytes", 0))
    alias = int(getattr(stats, "alias_size_in_bytes", 0))
    # the standard XLA accounting: aliased (donated) buffers are counted
    # in both argument and output sizes, so subtract them once
    peak = arg + out + temp + code - alias
    capacity = hbm_capacity_bytes(backend)
    report = {
        "name": name,
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "generated_code_bytes": code,
        "alias_bytes": alias,
        "peak_bytes": peak,
        "capacity_bytes": capacity,
        "headroom_frac": 1.0 - peak / capacity if capacity else None,
        "backend": backend or _default_backend(),
    }
    if record:
        _LAST_REPORT = report
        _TREND.append({"t": round(time.time(), 6), "peak_bytes": peak,
                       "headroom_frac": report["headroom_frac"]})
        reg = registry or get_registry()
        if reg.enabled:
            reg.gauge("memory/peak_hbm_bytes").set(peak)
            if report["headroom_frac"] is not None:
                reg.gauge("memory/hbm_headroom").set(
                    report["headroom_frac"])
            fields = dict(report)
            fields["step"] = fields.pop("name")  # "name" is the event's
            reg.event("memory", "step_memory", **fields)
    return report


def step_memory(fn, *args, backend=None, registry=None, record=True,
                name=None, **kwargs):
    """Memory report for one invocation of ``fn(*args, **kwargs)``
    (``fn`` a jitted callable, or any traceable — it is jitted on the
    fly). Host-side only: lowering reads avals, never runs the step.
    Returns the :func:`report_from_lowered` dict, or None when no
    analysis is available."""
    try:
        lower = getattr(fn, "lower", None)
        if lower is None:
            import jax

            lower = jax.jit(fn).lower
        lowered = lower(*args, **kwargs)
    except Exception:
        return None
    if name is None:
        name = getattr(fn, "__name__", None) or "step"
    return report_from_lowered(lowered, backend=backend,
                               registry=registry, record=record,
                               name=name)


# -- live buffer census -----------------------------------------------------

def live_buffer_census(top_k=10, *, labels=None):
    """Group the process's live device arrays into a top-K table.

    ``jax.live_arrays()`` grouped by (label, shape, dtype), descending
    by total bytes. Arrays carry no named scopes, so ``labels`` maps
    group names to pytrees whose leaves are matched by identity
    (``labels={"params": params, "opt_state": opt_state}``); unmatched
    arrays group under ``"<anon>"``. Returns ``{"total_arrays",
    "total_bytes", "groups": [{"label", "shape", "dtype", "count",
    "bytes"}, ...], "dropped_groups", "dropped_bytes"}``."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:
        arrays = []
    id_to_label = {}
    if labels:
        import jax

        for label, tree in labels.items():
            for leaf in jax.tree_util.tree_leaves(tree):
                id_to_label[id(leaf)] = label
    groups = {}
    total_bytes = 0
    total_arrays = 0
    for x in arrays:
        try:
            if x.is_deleted():
                continue
            nbytes = int(x.nbytes)
            key = (id_to_label.get(id(x), "<anon>"),
                   tuple(x.shape), str(x.dtype))
        except Exception:
            continue
        g = groups.setdefault(key, {"count": 0, "bytes": 0})
        g["count"] += 1
        g["bytes"] += nbytes
        total_bytes += nbytes
        total_arrays += 1
    rows = [{"label": label, "shape": list(shape), "dtype": dtype,
             "count": g["count"], "bytes": g["bytes"]}
            for (label, shape, dtype), g in groups.items()]
    rows.sort(key=lambda r: (-r["bytes"], r["label"], r["dtype"]))
    kept = rows[:top_k] if top_k else rows
    return {
        "total_arrays": total_arrays,
        "total_bytes": total_bytes,
        "groups": kept,
        "dropped_groups": max(0, len(rows) - len(kept)),
        "dropped_bytes": sum(r["bytes"] for r in rows[len(kept):]),
    }


# -- preflight --------------------------------------------------------------

def preflight(fn, *args, strict=False, capacity_bytes=None,
              safety_frac=0.0, backend=None, registry=None, **kwargs):
    """Estimate the step's peak HBM *before* dispatch and complain when
    it exceeds capacity: a warning by default, a
    :class:`MemoryBudgetError` with ``strict=True``. ``safety_frac``
    reserves a fraction of capacity (XLA's analysis is pre-fragmentation
    — real allocators need slack). Returns the step_memory report (None
    when the backend offers no analysis — never a false alarm)."""
    report = step_memory(fn, *args, backend=backend, registry=registry,
                         **kwargs)
    if report is None:
        return None
    capacity = capacity_bytes if capacity_bytes is not None \
        else report["capacity_bytes"]
    budget = int(capacity * (1.0 - safety_frac))
    report = dict(report, budget_bytes=budget,
                  over_budget=report["peak_bytes"] > budget)
    if report["over_budget"]:
        msg = (f"estimated peak HBM {report['peak_bytes'] / 1e9:.2f} GB "
               f"exceeds the {budget / 1e9:.2f} GB budget "
               f"({capacity / 1e9:.2f} GB capacity, "
               f"{safety_frac:.0%} safety margin) — this step will "
               f"RESOURCE_EXHAUSTED at dispatch; shrink the batch, "
               f"re-enable remat, or shard the optimizer state (ZeRO)")
        reg = registry or get_registry()
        if reg.enabled:
            reg.event("memory", "preflight_over_budget",
                      peak_bytes=report["peak_bytes"],
                      budget_bytes=budget, capacity_bytes=capacity)
        if strict:
            raise MemoryBudgetError(msg)
        warnings.warn(msg, stacklevel=2)
    return report


# -- OOM post-mortem --------------------------------------------------------

def is_oom_error(exc):
    """True when ``exc`` is an HBM exhaustion — XLA's
    ``RESOURCE_EXHAUSTED`` runtime error, or the synthetic one
    ``faults.inject_alloc_failure`` raises (same message marker, so the
    post-mortem path is testable on CPU)."""
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "Out of memory" in text
            or "out of memory" in text)


def resolve_dir(directory=None, registry=None):
    if directory:
        return directory
    env = os.environ.get(ENV_DIR)
    if env:
        return env
    reg = registry or get_registry()
    return reg.jsonl_dir or "."


# the most recent post-mortem record (with "path") — lets callers
# (bench, smoke stages) find what oom_guard dumped on their behalf,
# mirroring FlightRecorder.last_postmortem
_LAST_POSTMORTEM = None


def last_postmortem():
    """The most recent :func:`oom_postmortem` record this process wrote
    (None before the first)."""
    return _LAST_POSTMORTEM


def oom_postmortem(error=None, directory=None, *, registry=None,
                   census=None, labels=None, extra=None):
    """Write ``memory-postmortem-rank<N>.json`` (atomic tmp+rename;
    overwrites — the newest wreckage is the one that matters):
    the live-buffer census at death, the last :func:`step_memory`
    report, and the headroom trend — mirroring the numerics post-mortem
    format. Returns the record dict (with ``"path"``); also lands a
    ``memory`` event in the registry when enabled."""
    rank = _process_index()
    directory = resolve_dir(directory, registry)
    record = {
        "t": round(time.time(), 6),
        "reason": "resource_exhausted",
        "rank": rank,
        "error": None if error is None else
        f"{type(error).__name__}: {str(error)[:2000]}",
        "census": census if census is not None
        else live_buffer_census(labels=labels),
        "last_step_memory": _LAST_REPORT,
        "headroom_trend": headroom_trend(),
        "capacity_bytes": hbm_capacity_bytes(),
    }
    if extra:
        record.update(extra)
    path = os.path.join(directory, POSTMORTEM_BASENAME.format(rank=rank))
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
        os.replace(tmp, path)
        record["path"] = path
    except OSError:
        # an unwritable post-mortem dir must never mask the OOM itself
        record["path"] = None
    reg = registry or get_registry()
    if reg.enabled:
        reg.event("memory", "postmortem", path=record["path"],
                  error=record["error"],
                  census_bytes=record["census"]["total_bytes"],
                  trend_points=len(record["headroom_trend"]))
    global _LAST_POSTMORTEM
    _LAST_POSTMORTEM = record
    return record


@contextlib.contextmanager
def oom_guard(directory=None, *, registry=None, labels=None):
    """Run a block (typically one train-step dispatch + its host fetch)
    under the OOM post-mortem handler: a RESOURCE_EXHAUSTED escaping the
    block writes the post-mortem and re-raises as
    :class:`HBMExhaustedError` (with the original as ``__cause__``);
    every other exception passes through untouched."""
    try:
        yield
    except Exception as e:
        if isinstance(e, HBMExhaustedError) or not is_oom_error(e):
            raise
        record = oom_postmortem(e, directory, registry=registry,
                                labels=labels)
        raise HBMExhaustedError(
            f"step dispatch hit RESOURCE_EXHAUSTED — HBM is over "
            f"budget, not transiently busy. Memory post-mortem "
            f"(live-buffer census + headroom trend): "
            f"{record['path'] or '<unwritable dir>'}. Triage: "
            f"docs/resilience.md 'When a step OOMs'.") from e
