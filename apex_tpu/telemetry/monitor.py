"""Live monitoring control plane: rolling windows, alert rules,
OpenMetrics exposition.

Every other telemetry consumer in this repo is post-hoc — JSONL on
disk, read after the run. The :class:`Monitor` is the live plane: it
consumes the process registry (via the atomic
:meth:`~apex_tpu.telemetry.registry.MetricsRegistry.snapshot` and an
in-process event tap) plus, optionally, *tailed* JSONL from other
ranks/replicas, folds them into rolling time windows — counter deltas
and rates, gauge last values, histogram percentile snapshots — and
evaluates a declarative :class:`AlertRule` table against them. Rule
transitions fire structured ``alert`` events (``state="firing"`` /
``"resolved"`` with window evidence) through the same registry, so the
offline report (``tools/telemetry_report.py``) and the live plane see
one stream; the ``monitor/alerts_firing`` gauge is the one-number
summary.

Exposure is two-channel:

- :func:`render_openmetrics` — OpenMetrics/Prometheus text exposition
  of the current snapshot plus per-rule alert samples, optionally
  behind a stdlib ``http.server`` scrape endpoint
  (:meth:`Monitor.serve`, gated by ``APEX_TPU_MONITOR_PORT``). The
  renderer's output round-trips :func:`parse_openmetrics`, a strict
  conformance parser the tests and the oneproc smoke both run.
- ``tools/monitor_dash.py`` — terminal dashboard over a telemetry dir
  (live tail or ``--once``).

The **zero-overhead-off contract** holds end to end: a Monitor built
on a disabled registry installs no tap, starts no thread, opens no
socket, and emits nothing (``enabled`` is False and every method is a
no-op); nothing here ever touches compiled programs, so lowered HLO is
byte-identical with the monitor on or off. The ``monitor_overhead``
bench asserts the disabled leg emits zero monitor/alert events.

Window semantics (docs/observability.md#live-monitoring has the rule
table): each :meth:`Monitor.poll` appends one atomic snapshot to a
bounded history; counter rules measure the delta/rate between the
newest snapshot and the oldest one inside ``window_s``; gauge and
histogram rules read the newest snapshot (the histogram reservoir is
itself a sliding window of the last 4096 observations); sustain is
expressed in polls (``for_polls`` breached evaluations to fire,
``resolve_polls`` clean ones to resolve). The EWMA z-score rule is
event-driven: every matching ``span`` event updates an exponentially
weighted mean/variance and flags samples beyond ``threshold`` standard
deviations (after a warmup count), which the next poll reports.
"""

import collections
import fnmatch
import glob
import http.server
import json
import math
import os
import re
import threading

from apex_tpu.telemetry.attribution import PipelineAttributor
from apex_tpu.telemetry.registry import _process_index, get_registry

ENV_PORT = "APEX_TPU_MONITOR_PORT"

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")

SEVERITIES = ("info", "warn", "page")

RULE_KINDS = (
    "gauge_above",        # newest gauge value > threshold
    "gauge_below",        # newest gauge value < threshold
    "counter_increase",   # counter delta over window_s > threshold
    "counter_rate_above",  # counter delta/dt over window_s > threshold
    "hist_p99_above",     # histogram p99 (reservoir window) > threshold
    "ewma_z",             # |z| of a span duration vs EWMA baseline
    "replica_health",     # any fleet replica quarantined/respawning
    "recovery",           # supervisor failure -> recovery escalation
)

#: replica states the replica_health rule counts as down
BAD_REPLICA_STATES = ("quarantined", "respawning")


class AlertRule:
    """One declarative alert: a named condition over the rolling
    windows, with sustain/resolve hysteresis and a severity.

    ``metric`` is an ``fnmatch`` pattern for the metric-backed kinds
    (so ``fleet/ttft_*`` covers every tier) and a span name for
    ``ewma_z``; the ``replica_health`` / ``recovery`` kinds are
    event-driven and take no metric."""

    __slots__ = ("name", "kind", "metric", "threshold", "window_s",
                 "for_polls", "resolve_polls", "severity", "description")

    def __init__(self, name, kind, *, metric=None, threshold=None,
                 window_s=60.0, for_polls=1, resolve_polls=1,
                 severity="warn", description=""):
        if kind not in RULE_KINDS:
            raise ValueError(f"unknown rule kind {kind!r} "
                             f"(one of {RULE_KINDS})")
        if severity not in SEVERITIES:
            raise ValueError(f"unknown severity {severity!r} "
                             f"(one of {SEVERITIES})")
        if kind not in ("replica_health", "recovery"):
            if metric is None:
                raise ValueError(f"rule {name!r} ({kind}) needs a metric")
            if threshold is None:
                raise ValueError(
                    f"rule {name!r} ({kind}) needs a threshold")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.threshold = threshold
        self.window_s = float(window_s)
        self.for_polls = max(1, int(for_polls))
        self.resolve_polls = max(1, int(resolve_polls))
        self.severity = severity
        self.description = description

    def describe(self):
        return {"name": self.name, "kind": self.kind,
                "metric": self.metric, "threshold": self.threshold,
                "window_s": self.window_s, "for_polls": self.for_polls,
                "resolve_polls": self.resolve_polls,
                "severity": self.severity,
                "description": self.description}


def default_rules(*, ttft_slo_ms=None, pending_depth=64,
                  hbm_headroom_floor=0.05, goodput_floor=0.9,
                  step_time_z=4.0):
    """The stock rule table (docs/observability.md#live-monitoring).
    ``ttft_slo_ms`` maps tier name -> p99 budget in ms (default:
    interactive at 1000 ms); the other knobs parameterize one rule
    each."""
    if ttft_slo_ms is None:
        ttft_slo_ms = {"interactive": 1000.0}
    rules = [
        AlertRule(
            f"ttft_slo_{tier}", "hist_p99_above",
            metric=f"fleet/ttft_{tier}", threshold=float(ms),
            severity="page",
            description=f"{tier} TTFT p99 over its {ms:g} ms SLO")
        for tier, ms in sorted(ttft_slo_ms.items())
    ]
    rules += [
        AlertRule(
            "guard_skips", "gauge_above",
            metric="guard/consecutive_skips", threshold=0.0,
            severity="page",
            description="non-finite step guard is skipping steps"),
        AlertRule(
            "pending_depth", "gauge_above", metric="*/pending_depth",
            threshold=float(pending_depth), for_polls=3,
            description="admission backlog sustained over threshold"),
        AlertRule(
            "recompiles", "counter_increase", metric="compile/count",
            threshold=0.0, window_s=60.0,
            description="steady-state recompilation (shape-unstable "
                        "input leaking into a traced signature)"),
        AlertRule(
            "hbm_headroom", "gauge_below",
            metric="memory/hbm_headroom",
            threshold=float(hbm_headroom_floor), severity="page",
            description="HBM headroom under floor — next allocation "
                        "may RESOURCE_EXHAUSTED"),
        AlertRule(
            "goodput_ratio", "gauge_below",
            metric="recovery/goodput_step_ratio",
            threshold=float(goodput_floor),
            description="committed/dispatched step ratio dropped — "
                        "recovery replays are eating throughput"),
        AlertRule(
            "step_time_anomaly", "ewma_z", metric="train/step",
            threshold=float(step_time_z),
            description="step time beyond z EWMA standard deviations"),
        AlertRule(
            "replica_health", "replica_health", severity="page",
            description="a fleet replica is quarantined or awaiting "
                        "respawn"),
        AlertRule(
            "recovery_escalation", "recovery",
            description="training supervisor is mid-recovery"),
    ]
    return rules


class _RuleState:
    __slots__ = ("firing", "breach_streak", "ok_streak", "since_ts",
                 "fired_count", "value", "evidence")

    def __init__(self):
        self.firing = False
        self.breach_streak = 0
        self.ok_streak = 0
        self.since_ts = None
        self.fired_count = 0
        self.value = None
        self.evidence = None


class JsonlTailer:
    """Incremental reader of ``telemetry-rank*.jsonl`` files: remembers
    a byte offset per file, returns only complete new lines, parsed.
    ``skip_files`` (basenames) excludes e.g. this process's own sink —
    the Monitor already hears itself through the in-process tap."""

    PATTERN = "telemetry-rank*.jsonl"

    def __init__(self, dirpath, *, skip_files=()):
        self.dirpath = dirpath
        self._skip = frozenset(skip_files)
        self._offsets = {}

    def poll(self):
        records = []
        paths = sorted(glob.glob(os.path.join(self.dirpath,
                                              self.PATTERN)))
        for path in paths:
            if os.path.basename(path) in self._skip:
                continue
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                continue
            try:
                with open(path, "r", errors="replace") as fh:
                    fh.seek(offset)
                    chunk = fh.read()
            except OSError:
                continue
            end = chunk.rfind("\n")
            if end < 0:
                continue  # no complete line yet
            self._offsets[path] = offset + len(
                chunk[:end + 1].encode("utf-8", "replace"))
            for line in chunk[:end].splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
        return records


class Monitor:
    """The live evaluation loop. See the module docstring for the
    architecture; the short form::

        mon = Monitor(registry, rules=default_rules())
        ...
        mon.poll()            # evaluate once (tests drive this)
        mon.start(interval_s=1.0)   # or: background thread + scrape
        ...
        mon.close()

    Disabled registry => ``enabled`` is False and every method above is
    an inert no-op (no tap, no thread, no socket, no events).
    """

    def __init__(self, registry=None, *, rules=None, tail_dir=None,
                 ewma_alpha=0.25, ewma_warmup=8, history=128):
        self.registry = reg = registry or get_registry()
        self.enabled = bool(reg.enabled)
        self.rules = list(default_rules() if rules is None else rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.attribution = PipelineAttributor()
        self._lock = threading.RLock()
        self._states = {r.name: _RuleState() for r in self.rules}
        self._history = collections.deque(maxlen=max(2, int(history)))
        self._replicas = {}
        self._recovery = {"down": False, "cls": None, "step": None}
        self._ewma_alpha = float(ewma_alpha)
        self._ewma_warmup = int(ewma_warmup)
        self._ewma = {r.name: {"mean": None, "var": 0.0, "n": 0,
                               "anomaly": None}
                      for r in self.rules if r.kind == "ewma_z"}
        self._polls = 0
        self._thread = None
        self._stop = threading.Event()
        self._server = None
        self._server_thread = None
        self._tailer = None
        self._closed = False
        if not self.enabled:
            return
        if tail_dir:
            skip = ()
            if tail_dir == reg.jsonl_dir:
                skip = (f"telemetry-rank{_process_index()}.jsonl",)
            self._tailer = JsonlTailer(tail_dir, skip_files=skip)
        reg.add_event_tap(self._ingest)
        reg.event("monitor", "start", rules=names,
                  tail_dir=tail_dir or None)

    # -- intake -------------------------------------------------------------

    def _ingest(self, rec):
        """Event intake — called synchronously from the registry tap
        and for every tailed cross-rank record. Must stay cheap and
        must never raise into the emitter."""
        kind = rec.get("kind")
        if kind in ("alert", "monitor"):
            return  # our own output; never feed back
        if kind == "span":
            self.attribution.add_span(rec)
            name = rec.get("name")
            dur = rec.get("duration_s")
            if dur is None:
                return
            for rule in self.rules:
                if rule.kind == "ewma_z" and rule.metric == name:
                    self._ewma_update(rule, float(dur), rec)
        elif kind == "fleet" and rec.get("name") == "replica_state":
            with self._lock:
                self._replicas[rec.get("replica")] = rec.get("new")
        elif kind == "recovery":
            name = rec.get("name")
            if name == "failure":
                with self._lock:
                    self._recovery = {"down": True,
                                      "cls": rec.get("cls"),
                                      "step": rec.get("step")}
            elif name in ("recovered", "run_done"):
                with self._lock:
                    self._recovery = dict(self._recovery, down=False)

    def _ewma_update(self, rule, x, rec):
        with self._lock:
            st = self._ewma[rule.name]
            mean, var, n = st["mean"], st["var"], st["n"]
            if mean is not None and n >= self._ewma_warmup:
                std = math.sqrt(var) if var > 0 else 0.0
                if std > 0:
                    z = (x - mean) / std
                    if abs(z) > float(rule.threshold):
                        st["anomaly"] = {
                            "value_s": x, "z": round(z, 3),
                            "ewma_mean_s": mean,
                            "ewma_std_s": std,
                            "step": rec.get("step")}
            if mean is None:
                st["mean"], st["var"] = x, 0.0
            else:
                a = self._ewma_alpha
                d = x - mean
                st["mean"] = mean + a * d
                st["var"] = (1.0 - a) * (var + a * d * d)
            st["n"] = n + 1

    # -- evaluation ---------------------------------------------------------

    def _window_base(self, window_s, now_ts):
        """Oldest snapshot still inside the window (the counter rules'
        rate base); None before the second poll."""
        base = None
        for snap in self._history:
            if now_ts - snap["ts"] <= window_s:
                if base is None or snap["ts"] < base["ts"]:
                    base = snap
        return base

    def _check(self, rule, snap):
        """-> (breached, value, evidence dict)."""
        kind = rule.kind
        if kind in ("gauge_above", "gauge_below"):
            hits = {}
            worst = None
            for name, value in snap["gauges"].items():
                if value is None or not fnmatch.fnmatch(name,
                                                        rule.metric):
                    continue
                breach = (value > rule.threshold
                          if kind == "gauge_above"
                          else value < rule.threshold)
                if breach:
                    hits[name] = value
                    worst = (value if worst is None
                             else (max, min)[kind == "gauge_below"](
                                 worst, value))
            return bool(hits), worst, hits or None
        if kind == "hist_p99_above":
            hits = {}
            worst = None
            for name, summ in snap["histograms"].items():
                if not fnmatch.fnmatch(name, rule.metric):
                    continue
                p99 = summ.get("p99")
                if p99 is not None and p99 > rule.threshold:
                    hits[name] = {"p99": p99, "count": summ["count"]}
                    worst = p99 if worst is None else max(worst, p99)
            return bool(hits), worst, hits or None
        if kind in ("counter_increase", "counter_rate_above"):
            base = self._window_base(rule.window_s, snap["ts"])
            if base is None or base is snap:
                return False, None, None
            hits = {}
            worst = None
            dt = snap["ts"] - base["ts"]
            for name, value in snap["counters"].items():
                if not fnmatch.fnmatch(name, rule.metric):
                    continue
                delta = value - base["counters"].get(name, 0.0)
                measure = (delta if kind == "counter_increase"
                           else (delta / dt if dt > 0 else 0.0))
                if measure > rule.threshold:
                    hits[name] = {"delta": delta,
                                  "window_s": round(dt, 3)}
                    worst = (measure if worst is None
                             else max(worst, measure))
            return bool(hits), worst, hits or None
        if kind == "ewma_z":
            st = self._ewma[rule.name]
            anomaly, st["anomaly"] = st["anomaly"], None
            if anomaly is None:
                return False, None, None
            return True, anomaly["z"], anomaly
        if kind == "replica_health":
            bad = {str(idx): state
                   for idx, state in self._replicas.items()
                   if state in BAD_REPLICA_STATES}
            serving = snap["gauges"].get("fleet/replicas_serving")
            expected = snap["gauges"].get("fleet/replicas_expected")
            short = (serving is not None and expected is not None
                     and serving < expected)
            if not bad and not short:
                return False, None, None
            return True, float(len(bad)), {
                "replicas": bad or None, "serving": serving,
                "expected": expected}
        if kind == "recovery":
            rec = dict(self._recovery)
            gauge = snap["gauges"].get("recovery/in_recovery")
            down = rec.pop("down") or gauge == 1
            if not down:
                return False, None, None
            return True, 1.0, {k: v for k, v in rec.items()
                               if v is not None} or None
        raise AssertionError(f"unreachable rule kind {kind}")

    def poll(self):
        """One evaluation pass: tail cross-rank JSONL, take an atomic
        snapshot, evaluate every rule, emit firing/resolved ``alert``
        events, refresh ``monitor/alerts_firing``. Returns the
        evaluation dict (None when disabled)."""
        if not self.enabled:
            return None
        if self._tailer is not None:
            for rec in self._tailer.poll():
                self._ingest(rec)
        snap = self.registry.snapshot()
        transitions = []
        with self._lock:
            firing = 0
            results = []
            for rule in self.rules:
                breached, value, evidence = self._check(rule, snap)
                st = self._states[rule.name]
                st.value = value
                if breached:
                    st.evidence = evidence
                    st.breach_streak += 1
                    st.ok_streak = 0
                    if (not st.firing
                            and st.breach_streak >= rule.for_polls):
                        st.firing = True
                        st.since_ts = snap["ts"]
                        st.fired_count += 1
                        transitions.append(("firing", rule, st, None))
                else:
                    st.ok_streak += 1
                    st.breach_streak = 0
                    if st.firing and st.ok_streak >= rule.resolve_polls:
                        st.firing = False
                        dur = (snap["ts"] - st.since_ts
                               if st.since_ts is not None else None)
                        transitions.append(("resolved", rule, st, dur))
                if st.firing:
                    firing += 1
                results.append(self._row(rule, st))
            self._history.append(snap)
            self._polls += 1
        reg = self.registry
        for state, rule, st, dur in transitions:
            fields = {"state": state, "severity": rule.severity,
                      "rule_kind": rule.kind, "metric": rule.metric,
                      "threshold": rule.threshold,
                      "window_s": rule.window_s}
            if state == "firing":
                fields.update(value=st.value, evidence=st.evidence)
            else:
                fields.update(duration_s=(round(dur, 6)
                                          if dur is not None else None))
            reg.event("alert", rule.name, **fields)
            if state == "firing":
                reg.counter("monitor/alerts_fired").inc()
        reg.gauge("monitor/alerts_firing").set(float(firing))
        return {"ts": snap["ts"], "firing": firing, "alerts": results}

    @staticmethod
    def _row(rule, st):
        return {"rule": rule.name, "kind": rule.kind,
                "severity": rule.severity, "firing": st.firing,
                "value": st.value, "evidence": st.evidence,
                "since_ts": st.since_ts if st.firing else None,
                "fired_count": st.fired_count}

    def alerts(self):
        """Current per-rule state rows (the dashboard/exposition
        view)."""
        with self._lock:
            return [self._row(rule, self._states[rule.name])
                    for rule in self.rules]

    def alerts_firing(self):
        with self._lock:
            return sum(1 for st in self._states.values() if st.firing)

    def straggler_report(self, **kw):
        """Online pipeline attribution from the spans ingested so far
        (:meth:`PipelineAttributor.report`)."""
        return self.attribution.report(**kw)

    # -- exposition ---------------------------------------------------------

    def render_openmetrics(self):
        """OpenMetrics text of a fresh snapshot + current alerts."""
        if not self.enabled:
            return "# EOF\n"
        return render_openmetrics(self.registry.snapshot(),
                                  alerts=self.alerts())

    def serve(self, port=None):
        """Start the scrape endpoint on 127.0.0.1:``port`` (default:
        ``$APEX_TPU_MONITOR_PORT``; port 0 binds an ephemeral port —
        read it back from ``bound_port``). No-op returning None when
        disabled or no port is configured."""
        if not self.enabled or self._server is not None:
            return self._server
        if port is None:
            raw = os.environ.get(ENV_PORT)
            if not raw:
                return None
            port = int(raw)
        monitor = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = monitor.render_openmetrics().encode("utf-8")
                except Exception as exc:  # pragma: no cover
                    self.send_error(500, str(exc)[:100])
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 OPENMETRICS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrape noise must not hit stderr

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", int(port)),
                                              Handler)
        srv.daemon_threads = True
        self._server = srv
        self._server_thread = threading.Thread(
            target=srv.serve_forever, name="apex-tpu-monitor-scrape",
            daemon=True)
        self._server_thread.start()
        self.registry.event("monitor", "scrape_endpoint",
                            port=self.bound_port)
        return srv

    @property
    def bound_port(self):
        return (self._server.server_address[1]
                if self._server is not None else None)

    # -- lifecycle ----------------------------------------------------------

    def start(self, interval_s=1.0):
        """Background evaluation: a daemon thread polling every
        ``interval_s`` seconds, plus the scrape endpoint when
        ``$APEX_TPU_MONITOR_PORT`` is set. No-op when disabled.
        Returns self."""
        if not self.enabled or self._thread is not None:
            return self
        self.serve()
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception:  # pragma: no cover
                    pass  # a broken rule must never kill the loop

        self._thread = threading.Thread(
            target=loop, name="apex-tpu-monitor", daemon=True)
        self._thread.start()
        return self

    def close(self):
        """Stop the loop and the scrape endpoint, detach from the
        registry, emit the ``monitor``/``stop`` event. Idempotent."""
        if not self.enabled or self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            if self._server_thread is not None:
                self._server_thread.join(timeout=5.0)
                self._server_thread = None
        self.registry.remove_event_tap(self._ingest)
        self.registry.event("monitor", "stop", polls=self._polls,
                            alerts_total=sum(
                                st.fired_count
                                for st in self._states.values()))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- OpenMetrics exposition -------------------------------------------------

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name, prefix="apex_tpu_"):
    out = prefix + _SANITIZE.sub("_", str(name))
    if not _NAME_OK.match(out):
        out = prefix + "invalid"
    return out


def _fmt(value):
    value = float(value)
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return repr(value)


def _label_escape(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_openmetrics(snapshot, alerts=(), *, prefix="apex_tpu_"):
    """Render a registry snapshot (plus optional alert rows from
    :meth:`Monitor.alerts`) as OpenMetrics text exposition.

    Naming: ``/``-separated metric names sanitize to ``_`` under the
    ``apex_tpu_`` prefix (``fleet/ttft_interactive`` ->
    ``apex_tpu_fleet_ttft_interactive``). Counters expose a single
    ``_total`` sample; gauges their last value (unset gauges are
    omitted); histograms map to ``summary`` families — ``{quantile=
    "0.5"|"0.99"}`` over the reservoir window plus exact ``_count`` /
    ``_sum``. Firing alerts are ``apex_tpu_monitor_alert{rule=...,
    severity=...} 1`` samples. Output terminates with ``# EOF`` and
    round-trips :func:`parse_openmetrics`."""
    lines = []
    seen = set()

    def family(name, mtype):
        if name in seen:
            return False
        seen.add(name)
        lines.append(f"# TYPE {name} {mtype}")
        return True

    for raw in sorted(snapshot.get("counters", {})):
        name = _metric_name(raw, prefix)
        if family(name, "counter"):
            lines.append(
                f"{name}_total "
                f"{_fmt(snapshot['counters'][raw])}")
    for raw in sorted(snapshot.get("gauges", {})):
        value = snapshot["gauges"][raw]
        if value is None:
            continue
        name = _metric_name(raw, prefix)
        if family(name, "gauge"):
            lines.append(f"{name} {_fmt(value)}")
    for raw in sorted(snapshot.get("histograms", {})):
        summ = snapshot["histograms"][raw]
        name = _metric_name(raw, prefix)
        if not family(name, "summary"):
            continue
        for q, key in (("0.5", "p50"), ("0.99", "p99")):
            if summ.get(key) is not None:
                lines.append(
                    f'{name}{{quantile="{q}"}} {_fmt(summ[key])}')
        lines.append(f"{name}_count {int(summ.get('count') or 0)}")
        lines.append(f"{name}_sum {_fmt(summ.get('total') or 0.0)}")
    firing = [row for row in alerts if row.get("firing")]
    if firing:
        name = prefix + "monitor_alert"
        if family(name, "gauge"):
            for row in firing:
                lines.append(
                    f'{name}{{rule="{_label_escape(row["rule"])}",'
                    f'severity="{_label_escape(row["severity"])}"}} 1')
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


_TYPE_LINE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|summary|histogram|info|stateset|unknown)$")
_SAMPLE_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (\S+)$")
_LABEL_BODY = re.compile(
    r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\["\\n])*)"$')
_VALUE_OK = re.compile(r"^(NaN|[+-]Inf|[+-]?[0-9]*\.?[0-9]+"
                       r"([eE][+-]?[0-9]+)?)$")

_SUFFIXES = ("_total", "_count", "_sum", "_bucket", "_created")


def _family_of(sample_name, declared):
    if sample_name in declared:
        return sample_name, ""
    for suffix in _SUFFIXES:
        if sample_name.endswith(suffix):
            base = sample_name[:-len(suffix)]
            if base in declared:
                return base, suffix
    return None, None


def parse_openmetrics(text):
    """Strict conformance parser for the renderer's output: validates
    metric-name / label / value syntax, TYPE-before-sample ordering,
    one TYPE per family, counter ``_total`` naming, summary suffix
    discipline, and the terminal ``# EOF``. Raises ``ValueError`` with
    the offending line on any violation; returns ``{family: {"type":
    ..., "samples": [(name, labels, value), ...]}}``."""
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition must terminate with '# EOF'")
    families = {}
    for i, line in enumerate(lines[:-1], 1):
        if line == "# EOF":
            raise ValueError(f"line {i}: '# EOF' before end of input")
        if line.startswith("#"):
            if line.startswith("# HELP "):
                continue
            m = _TYPE_LINE.match(line)
            if m is None:
                raise ValueError(f"line {i}: malformed comment/TYPE "
                                 f"line: {line!r}")
            name, mtype = m.group(1), m.group(2)
            if name in families:
                raise ValueError(
                    f"line {i}: duplicate TYPE for {name!r}")
            families[name] = {"type": mtype, "samples": []}
            continue
        m = _SAMPLE_LINE.match(line)
        if m is None:
            raise ValueError(f"line {i}: malformed sample: {line!r}")
        sample_name, label_blob, value = m.groups()
        family, suffix = _family_of(sample_name, families)
        if family is None:
            raise ValueError(
                f"line {i}: sample {sample_name!r} has no preceding "
                f"TYPE line")
        mtype = families[family]["type"]
        if mtype == "counter" and suffix not in ("_total", "_created"):
            raise ValueError(
                f"line {i}: counter sample must use the _total "
                f"suffix: {sample_name!r}")
        if mtype == "gauge" and suffix:
            raise ValueError(
                f"line {i}: gauge sample must not carry suffix "
                f"{suffix!r}")
        if mtype == "summary" and suffix not in ("", "_count", "_sum",
                                                 "_created"):
            raise ValueError(
                f"line {i}: invalid summary suffix {suffix!r}")
        labels = {}
        if label_blob:
            body = label_blob[1:-1]
            if body:
                for part in body.split(","):
                    lm = _LABEL_BODY.match(part)
                    if lm is None:
                        raise ValueError(
                            f"line {i}: malformed label {part!r}")
                    labels[lm.group(1)] = lm.group(2)
        if mtype == "summary" and suffix == "" and \
                "quantile" not in labels:
            raise ValueError(
                f"line {i}: bare summary sample needs a quantile "
                f"label: {line!r}")
        if not _VALUE_OK.match(value):
            raise ValueError(f"line {i}: malformed value {value!r}")
        families[family]["samples"].append(
            (sample_name, labels, value))
    return families
