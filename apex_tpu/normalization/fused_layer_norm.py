"""FusedLayerNorm / FusedRMSNorm modules and functional entry points.

Parity: reference apex/normalization/fused_layer_norm.py —
``FusedLayerNorm`` (204), ``FusedRMSNorm`` (300), ``MixedFusedLayerNorm``
(398), ``MixedFusedRMSNorm`` (420), functional wrappers
``fused_layer_norm[_affine]`` / ``fused_rms_norm[_affine]`` (168-201) and
``manual_rms_norm`` (16-29).

TPU design: modules are flax.linen Modules; the math lives in
:mod:`apex_tpu.ops.layer_norm` — Pallas kernels from
:mod:`apex_tpu.kernels.norm` behind the kernel registry's
``layernorm``/``rmsnorm`` gates (docs/kernels.md), the jnp oracle
everywhere else.
"Mixed" variants compute in fp32 but return the *parameter* dtype, matching
the reference's mixed-dtype kernels (layer_norm_cuda.cpp
``forward_affine_mixed_dtypes``).
"""

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops import layer_norm as _ln_ops

Shape = Union[int, Sequence[int]]


def _norm_shape(normalized_shape: Shape):
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(normalized_shape)


# -- functional API (reference fused_layer_norm.py:168-201) -----------------

def fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6):
    return _ln_ops.layer_norm(input, normalized_shape, weight, bias, eps)


def fused_layer_norm(input, normalized_shape, eps=1e-6):
    return _ln_ops.layer_norm(input, normalized_shape, None, None, eps)


def fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6):
    return _ln_ops.rms_norm(input, normalized_shape, weight, eps)


def fused_rms_norm(input, normalized_shape, eps=1e-6):
    return _ln_ops.rms_norm(input, normalized_shape, None, eps)


def mixed_dtype_fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6):
    return _ln_ops.layer_norm(input, normalized_shape, weight, bias, eps,
                              out_dtype=weight.dtype)


def mixed_dtype_fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6):
    return _ln_ops.rms_norm(input, normalized_shape, weight, eps,
                            out_dtype=weight.dtype)


def manual_rms_norm(input, normalized_shape, weight, eps):
    """Pure-jnp RMSNorm reference (reference fused_layer_norm.py:16-29)."""
    dims = tuple(range(-len(_norm_shape(normalized_shape)), 0))
    variance = jnp.mean(jnp.square(input.astype(jnp.float32)), axis=dims, keepdims=True)
    out = input * jnp.reciprocal(jnp.sqrt(variance + eps))
    if weight is None:
        return out.astype(input.dtype)
    if weight.dtype in [jnp.float16, jnp.bfloat16]:
        out = out.astype(weight.dtype)
    return (weight * out).astype(weight.dtype)


# -- module API -------------------------------------------------------------

class FusedLayerNorm(nn.Module):
    """LayerNorm module (reference FusedLayerNorm, fused_layer_norm.py:204).

    Usage: ``FusedLayerNorm(normalized_shape=h)`` then ``.apply({'params': p}, x)``.
    """

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32
    memory_efficient: bool = False  # accepted for parity; recompute is jax.checkpoint's job

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, shape, self.param_dtype)
            return _ln_ops.layer_norm(x, shape, weight, bias, self.eps)
        return _ln_ops.layer_norm(x, shape, None, None, self.eps)


class FusedRMSNorm(nn.Module):
    """RMSNorm module (reference FusedRMSNorm, fused_layer_norm.py:300)."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            return _ln_ops.rms_norm(x, shape, weight, self.eps)
        return _ln_ops.rms_norm(x, shape, None, self.eps)


class MixedFusedLayerNorm(FusedLayerNorm):
    """LayerNorm whose output dtype follows the parameter dtype
    (reference MixedFusedLayerNorm, fused_layer_norm.py:398)."""

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, shape, self.param_dtype)
        return mixed_dtype_fused_layer_norm_affine(x, weight, bias, shape, self.eps)


class MixedFusedRMSNorm(FusedRMSNorm):
    """RMSNorm whose output dtype follows the parameter dtype
    (reference MixedFusedRMSNorm, fused_layer_norm.py:420)."""

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
        return mixed_dtype_fused_rms_norm_affine(x, weight, shape, self.eps)
