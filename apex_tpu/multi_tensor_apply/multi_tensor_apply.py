"""Multi-tensor apply dispatcher.

Parity: reference apex/multi_tensor_apply/multi_tensor_apply.py:3-30 —
``multi_tensor_applier(op, noop_flag_buf, tensor_lists, *args)`` dispatching
to chunked CUDA kernels with ``chunk_size=2048*32``.

TPU design: chunking exists on GPU to bound per-launch tensor counts
(csrc/multi_tensor_apply.cuh:15-26). Under XLA there are no launches to
bound; the applier simply calls the functional op and returns its results.
``chunk_size`` is accepted and ignored for API parity. Ops are pure
functions; callers thread the returned arrays (and the overflow flag)
through their own state.
"""


class MultiTensorApply(object):
    available = True
    warned = False

    def __init__(self, chunk_size=2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args, **kwargs):
        """Apply ``op`` to ``tensor_lists``.

        Unlike the CUDA version this is functional: the op's outputs are
        returned rather than written in place.
        """
        return op(noop_flag, tensor_lists, *args, **kwargs)


multi_tensor_applier = MultiTensorApply(2048 * 32)
