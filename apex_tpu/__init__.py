"""apex_tpu — a TPU-native framework with the capabilities of NVIDIA Apex.

This is a ground-up JAX/XLA/Pallas re-design of the capabilities of the
reference (juncongmoo/apex, mounted at /root/reference):

- ``apex_tpu.amp``            — mixed precision (bf16 autocast, loss scaling),
  parity with ``apex/amp`` (reference apex/amp/frontend.py:197).
- ``apex_tpu.optimizers``     — fused optimizers (Adam/LAMB/SGD/NovoGrad/
  Adagrad/MixedPrecisionLamb), parity with ``apex/optimizers``.
- ``apex_tpu.multi_tensor_apply`` — the multi-tensor-apply engine
  (reference apex/multi_tensor_apply/multi_tensor_apply.py:24-30).
- ``apex_tpu.normalization``  — FusedLayerNorm / FusedRMSNorm backed by
  Pallas TPU kernels (reference apex/normalization/fused_layer_norm.py).
- ``apex_tpu.parallel``       — data-parallel runtime: DistributedDataParallel
  semantics over an XLA ``psum``, SyncBatchNorm, LARC
  (reference apex/parallel/).
- ``apex_tpu.transformer``    — Megatron-style tensor/pipeline/sequence
  parallelism over a ``jax.sharding.Mesh`` (reference apex/transformer/).
- ``apex_tpu.contrib``        — fused extras: xentropy, clip_grad, focal loss,
  flash attention, fused dense/MLP (reference apex/contrib/).
- ``apex_tpu.models``         — ResNet, GPT, BERT, DCGAN model families used
  by the examples and benchmarks (reference examples/, apex/transformer/testing/).
- ``apex_tpu.telemetry``      — unified tracing/metrics/XLA cost accounting
  (spans, collective byte counters, MFU from ``cost_analysis()``); no
  reference counterpart — see docs/observability.md.

Design notes (TPU-first, not a port):
- CUDA multi-tensor kernels -> one jitted update over the parameter pytree;
  XLA fuses the elementwise work. Hot spots use Pallas kernels.
- NCCL process groups      -> mesh axis names + lax collectives over ICI/DCN.
- CUDA streams / hooks     -> XLA latency-hiding scheduler inside one jit.
- fp16 + loss scaling      -> bf16 by default (scaler kept for API parity and
  for explicit fp16 use).
"""

import logging as _pylogging

__version__ = "0.1.0"

# --- jax version compat -----------------------------------------------------
# The codebase targets the current jax API (jax.shard_map with check_vma,
# lax.axis_size). Driver/CI containers may carry an older jax (0.4.x) where
# shard_map lives in jax.experimental with the check_rep spelling and
# axis_size does not exist; install the two shims once here so every call
# site works unchanged on both.
import jax as _jax
from jax import lax as _lax

if not hasattr(_lax, "axis_size"):
    def _axis_size_shim(axis_name):
        # the documented old-jax idiom: a psum of the constant 1 is folded
        # to the concrete axis size (raises NameError when unbound, same
        # contract as the modern lax.axis_size)
        return _lax.psum(1, axis_name)

    _lax.axis_size = _axis_size_shim

if not hasattr(_jax, "shard_map"):
    def _shard_map_shim(f, *, mesh, in_specs, out_specs, check_vma=False,
                        **kw):
        from jax.experimental.shard_map import shard_map as _sm

        # check_vma=False is the repo-wide setting (the custom-vjp
        # collective ops defeat the old rep checker too); map it onto
        # check_rep and default it off.
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma, **kw)

    _jax.shard_map = _shard_map_shim

try:  # pltpu.CompilerParams was TPUCompilerParams before jax 0.6
    from jax.experimental.pallas import tpu as _pltpu

    if not hasattr(_pltpu, "CompilerParams") and \
            hasattr(_pltpu, "TPUCompilerParams"):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except Exception:  # pallas unavailable on this backend: kernels gate off
    pass
# ---------------------------------------------------------------------------

from apex_tpu._logging import RankInfoFormatter, deprecated_warning  # noqa: F401

# Light-weight subpackages are imported eagerly so `import apex_tpu` gives the
# same surface as `import apex` (reference apex/__init__.py imports amp etc.
# lazily behind try/except; we are pure-Python+JAX so imports are cheap).
from apex_tpu import telemetry  # noqa: F401
from apex_tpu import analysis  # noqa: F401
from apex_tpu import multi_tensor_apply  # noqa: F401
from apex_tpu import optimizers  # noqa: F401
from apex_tpu import normalization  # noqa: F401
from apex_tpu import amp  # noqa: F401
from apex_tpu import parallel  # noqa: F401
from apex_tpu import fp16_utils  # noqa: F401
from apex_tpu import resilience  # noqa: F401
from apex_tpu import transformer  # noqa: F401

_pylogging.getLogger(__name__).addHandler(_pylogging.NullHandler())
