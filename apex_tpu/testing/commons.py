"""Testing harness helpers.

Parity: reference apex/transformer/testing/commons.py (296 LoC — model
providers, initialize_distributed, set_random_seed) and
distributed_test_base.py (spawned multi-process test bases). On TPU the
multi-process harness becomes SPMD ``shard_map`` over a virtual device
mesh; this module centralizes the wrapper used across the test suite.
"""

import functools

import jax
import numpy as np


def shard_map(fn=None, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` with vma (replication) checking disabled.

    The apex_tpu collective region ops are custom-vjp pairs whose
    replication typing JAX's static vma checker cannot always infer
    (e.g. psum-in-backward of an identity forward); runtime semantics are
    still exactly SPMD. Usable as a decorator or a function.
    """
    def wrap(f):
        if hasattr(jax, "shard_map"):
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        # jax < 0.6: shard_map lives in jax.experimental and the
        # replication checker is spelled check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

    if fn is None:
        return wrap
    return wrap(fn)


def tp_shard_map(mesh, in_specs, out_specs):
    return functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)


def set_random_seed(seed: int):
    """Reference commons.py set_random_seed: seed all RNG streams."""
    np.random.seed(seed)
    from apex_tpu.transformer.tensor_parallel.random import (
        model_parallel_xla_manual_seed,
    )

    model_parallel_xla_manual_seed(seed)
    return jax.random.PRNGKey(seed)
