from apex_tpu.testing.commons import (  # noqa: F401
    set_random_seed,
    shard_map,
    tp_shard_map,
)
