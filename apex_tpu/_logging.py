"""Rank-aware logging.

Parity: reference apex/__init__.py:31-43 (``RankInfoFormatter`` injects the
(dp, tp, pp, vpp) rank tuple into every record) and apex/__init__.py:62-68
(``deprecated_warning``).

On TPU there is one Python process per host rather than per chip, so the
"rank" is the JAX process index plus the model-parallel ranks registered with
``apex_tpu.transformer.parallel_state`` (which are mesh-coordinate based).
"""

import logging
import warnings

# Resolved providers, cached after first successful import: every log
# record used to re-run the import machinery (and swallow the resulting
# exceptions) inside the formatter — pure overhead on the hot logging
# path. False = import failed (don't retry per record); the
# *initialization state* stays dynamic: parallel_state may become
# initialized after the first record, so only the module lookup is
# cached, not the answer.
_PARALLEL_STATE = None
_JAX = None


def _get_rank_info():
    global _PARALLEL_STATE, _JAX
    if _PARALLEL_STATE is None:
        try:
            from apex_tpu.transformer import parallel_state

            _PARALLEL_STATE = parallel_state
        except Exception:
            _PARALLEL_STATE = False
    if _PARALLEL_STATE:
        try:
            if _PARALLEL_STATE.model_parallel_is_initialized():
                return _PARALLEL_STATE.get_rank_info()
        except Exception:
            pass
    if _JAX is None:
        try:
            import jax

            _JAX = jax
        except Exception:
            _JAX = False
    if _JAX:
        try:
            return (_JAX.process_index(),)
        except Exception:
            pass
    return (0,)


class RankInfoFormatter(logging.Formatter):
    """Formatter prefixing each record with the parallel rank tuple."""

    def format(self, record):
        record.rank_info = str(_get_rank_info())
        return super().format(record)


def deprecated_warning(msg: str) -> None:
    """Emit a deprecation warning once (reference apex/__init__.py:62-68)."""
    warnings.warn(msg, DeprecationWarning, stacklevel=3)
