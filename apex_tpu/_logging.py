"""Rank-aware logging.

Parity: reference apex/__init__.py:31-43 (``RankInfoFormatter`` injects the
(dp, tp, pp, vpp) rank tuple into every record) and apex/__init__.py:62-68
(``deprecated_warning``).

On TPU there is one Python process per host rather than per chip, so the
"rank" is the JAX process index plus the model-parallel ranks registered with
``apex_tpu.transformer.parallel_state`` (which are mesh-coordinate based).
"""

import logging
import warnings


def _get_rank_info():
    try:
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            return parallel_state.get_rank_info()
    except Exception:
        pass
    try:
        import jax

        return (jax.process_index(),)
    except Exception:
        return (0,)


class RankInfoFormatter(logging.Formatter):
    """Formatter prefixing each record with the parallel rank tuple."""

    def format(self, record):
        record.rank_info = str(_get_rank_info())
        return super().format(record)


def deprecated_warning(msg: str) -> None:
    """Emit a deprecation warning once (reference apex/__init__.py:62-68)."""
    warnings.warn(msg, DeprecationWarning, stacklevel=3)
