"""Autocast interop helpers.

Parity: reference apex/_autocast_utils.py — ``_get_autocast_dtypes`` (9-12)
and ``_cast_if_autocast_enabled`` (22-26), used by custom autograd
functions so they respect an ambient torch autocast context.

TPU design: the ambient context is apex_tpu's amp O1 policy
(:mod:`apex_tpu.amp.policy`); these helpers consult it so fused ops cast
their inputs the same way patched ops do.
"""

from typing import Sequence

import jax.numpy as jnp

from apex_tpu.amp._amp_state import _amp_state


def _get_autocast_dtypes() -> Sequence:
    """Dtypes an autocast region may produce (reference: [half, float] or
    [bfloat16, half, float])."""
    return [jnp.bfloat16, jnp.float16, jnp.float32]


def _get_current_dtype(dtype=None):
    """The active autocast compute dtype, or ``dtype`` when given
    (reference _autocast_utils.py:15-19)."""
    if dtype is not None:
        return dtype
    opt_properties = getattr(_amp_state, "opt_properties", None)
    if opt_properties is not None and getattr(opt_properties, "enabled", False):
        return getattr(opt_properties, "cast_model_type", None) or jnp.bfloat16
    return jnp.float32


def _cast_if_autocast_enabled(*args):
    """Cast floating args to the active autocast dtype when amp O1 casting
    is enabled; identity otherwise (reference _autocast_utils.py:22-26)."""
    opt_properties = getattr(_amp_state, "opt_properties", None)
    enabled = (opt_properties is not None
               and getattr(opt_properties, "patch_torch_functions", False))
    if not enabled:
        return args
    target = _get_current_dtype()

    def cast(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(target)
        return a

    return tuple(cast(a) for a in args)
