"""FusedSGD — SGD with momentum in one fused step.

Parity: reference apex/optimizers/fused_sgd.py:6-227 (momentum, dampening,
nesterov, weight_decay, wd_after_momentum, materialize_master_grads). The
reference unscales fp16 grads *inside* the step when driven by amp
(fused_sgd.py:148-209); here that is the ``scale`` argument.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_sgd
from apex_tpu.optimizers._base import (
    FusedOptimizerBase,
    resolve_found_inf,
    zeros_like_tree,
)


class FusedSGD(FusedOptimizerBase):
    def __init__(self, lr=None, momentum=0.0, dampening=0.0, weight_decay=0.0,
                 nesterov=False, wd_after_momentum=False,
                 materialize_master_grads=True, set_grad_none=False):
        if lr is None:
            raise ValueError("FusedSGD requires a learning rate")
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "momentum_buffer": zeros_like_tree(params),
        }

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        lr = self.lr if lr is None else lr
        noop = resolve_found_inf(found_inf)
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        first_run = state["step"] == 0
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state["momentum_buffer"])
        new_p, new_m, _ = multi_tensor_applier(
            multi_tensor_sgd, noop, [g_leaves, p_leaves, m_leaves],
            self.weight_decay, self.momentum, self.dampening, lr,
            self.nesterov, first_run, self.wd_after_momentum, 1.0 / scale)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step,
             "momentum_buffer": jax.tree_util.tree_unflatten(treedef, new_m)},
        )
