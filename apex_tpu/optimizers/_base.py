"""Shared machinery for fused optimizers.

The reference optimizers operate on torch param_groups segregated by dtype
(apex/optimizers/fused_adam.py:133-167). The TPU equivalents operate on JAX
pytrees: ``init`` builds a state pytree, ``step`` is a pure jittable function
``(grads, state, params) -> (new_params, new_state)``. Overflow skipping is
branch-free (``jnp.where`` on a ``found_inf`` scalar), mirroring the
reference's ``capturable`` CUDA-graph path (fused_adam.py:171-229).
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


def tree_leaves_and_def(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def zeros_like_tree(params, dtype=jnp.float32):
    return jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, dtype), params)


def cast_tree(params, dtype):
    return jax.tree_util.tree_map(lambda p: p.astype(dtype), params)


def master_copy_tree(params, dtype=None):
    """Alias-free cast for fp32 master-weight creation.

    ``astype`` is a no-op on leaves already at ``dtype`` and returns the
    SAME buffer — a master tree built that way aliases the model params
    wherever they are already fp32 (all norm params under amp O2), and a
    train step donating both params and opt_state then presents one
    buffer twice to XLA: "Attempt to donate the same buffer twice in
    Execute()" (the round-3 'ResNet donation INVALID_ARGUMENT').
    ``jnp.array(..., copy=True)`` forces a distinct buffer for every
    leaf. The contract is enforced statically by the
    ``double-donation`` lint rule (apex_tpu.analysis, caught at trace
    time; regression in tests/L0/test_analysis.py).
    """
    dtype = jnp.float32 if dtype is None else dtype
    return jax.tree_util.tree_map(
        lambda p: jnp.array(p, dtype=dtype, copy=True), params)


class FusedOptimizerBase:
    """Base class giving the stateful-eager and optax views of a stepper."""

    def init(self, params) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        raise NotImplementedError

    # -- optax interop ------------------------------------------------------
    def as_gradient_transformation(self):
        """Return an optax.GradientTransformation computing ``new - old``
        updates so that ``optax.apply_updates`` matches ``self.step``."""
        import optax

        def init_fn(params):
            return {"inner": self.init(params), "params": params}

        def update_fn(grads, state, params=None):
            if params is None:
                params = state["params"]
            new_params, new_inner = self.step(grads, state["inner"], params)
            updates = jax.tree_util.tree_map(
                lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
                new_params, params)
            return updates, {"inner": new_inner, "params": new_params}

        return optax.GradientTransformation(init_fn, update_fn)


def resolve_found_inf(found_inf):
    if found_inf is None:
        return jnp.zeros((), jnp.float32)
    return jnp.asarray(found_inf, jnp.float32).reshape(())
