"""FusedLAMB — layer-wise adaptive moments with trust ratio.

Parity: reference apex/optimizers/fused_lamb.py:4-215: global grad norm via
two ``multi_tensor_l2norm`` calls (124-133), then one fused lamb update with
per-layer trust ratios and global grad clipping (183-199).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_l2norm, multi_tensor_lamb
from apex_tpu.optimizers._base import (
    FusedOptimizerBase,
    resolve_found_inf,
    zeros_like_tree,
)


class FusedLAMB(FusedOptimizerBase):
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, amsgrad=False,
                 adam_w_mode=True, grad_averaging=True, set_grad_none=True,
                 max_grad_norm=1.0, use_nvlamb=False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": zeros_like_tree(params),
            "exp_avg_sq": zeros_like_tree(params),
        }

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        lr = self.lr if lr is None else lr
        noop = resolve_found_inf(found_inf)
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        g_leaves = [g.astype(jnp.float32) / scale for g in g_leaves]
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        v_leaves = treedef.flatten_up_to(state["exp_avg_sq"])
        # Global grad norm (reference fused_lamb.py:124-133 computes one norm
        # per dtype bucket then combines; with fp32 grads one call suffices).
        gnorm, _ = multi_tensor_applier(multi_tensor_l2norm, noop, [g_leaves])
        mode = 1 if self.adam_w_mode else 0
        new_p, new_m, new_v, _ = multi_tensor_applier(
            multi_tensor_lamb, noop, [g_leaves, p_leaves, m_leaves, v_leaves],
            lr, self.betas[0], self.betas[1], self.eps, step,
            self.bias_correction, self.weight_decay, self.grad_averaging,
            mode, gnorm, self.max_grad_norm, self.use_nvlamb)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step,
             "exp_avg": jax.tree_util.tree_unflatten(treedef, new_m),
             "exp_avg_sq": jax.tree_util.tree_unflatten(treedef, new_v)},
        )
