"""FusedAdam — Adam/AdamW over the whole parameter pytree in one fused step.

Parity: reference apex/optimizers/fused_adam.py:4-271 (``adam_w_mode``,
``bias_correction``, ``capturable`` semantics, ``master_weights``). On TPU
the step is always jit-compiled, so the ``capturable`` distinction
disappears: learning rate and step count live on-device and overflow skip is
branch-free.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_adam, multi_tensor_adam_capturable_master
from apex_tpu.optimizers._base import (
    FusedOptimizerBase,
    master_copy_tree,
    resolve_found_inf,
    zeros_like_tree,
)


class FusedAdam(FusedOptimizerBase):
    """Adam/AdamW.

    Args mirror the reference (apex/optimizers/fused_adam.py:60-103):
      lr, bias_correction, betas, eps, adam_w_mode, weight_decay, amsgrad
      (unsupported, as in the reference), set_grad_none (meaningless in JAX),
      capturable (always-on under jit), master_weights (keep fp32 masters for
      low-precision params).
    """

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0, amsgrad=False,
                 set_grad_none=True, capturable=True, master_weights=False):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.master_weights = master_weights

    def init(self, params):
        state = {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": zeros_like_tree(params),
            "exp_avg_sq": zeros_like_tree(params),
        }
        if self.master_weights:
            state["master"] = master_copy_tree(params)
        return state

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        lr = self.lr if lr is None else lr
        noop = resolve_found_inf(found_inf)
        # Step only advances on non-overflow iterations (capturable semantics,
        # reference fused_adam.py:196-204).
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        v_leaves = treedef.flatten_up_to(state["exp_avg_sq"])
        mode = 1 if self.adam_w_mode else 0
        inv_scale = 1.0 / scale
        if self.master_weights:
            mw_leaves = treedef.flatten_up_to(state["master"])
            new_p, new_m, new_v, new_mw, _ = multi_tensor_applier(
                multi_tensor_adam_capturable_master, noop,
                [g_leaves, p_leaves, m_leaves, v_leaves, mw_leaves],
                lr, self.betas[0], self.betas[1], self.eps, step, mode,
                self.bias_correction, self.weight_decay, inv_scale)
        else:
            g_leaves = [g.astype(jnp.float32) * inv_scale for g in g_leaves]
            new_p, new_m, new_v, _ = multi_tensor_applier(
                multi_tensor_adam, noop,
                [g_leaves, p_leaves, m_leaves, v_leaves],
                lr, self.betas[0], self.betas[1], self.eps, step, mode,
                self.bias_correction, self.weight_decay)
        new_state = {
            "step": step,
            "exp_avg": jax.tree_util.tree_unflatten(treedef, new_m),
            "exp_avg_sq": jax.tree_util.tree_unflatten(treedef, new_v),
        }
        if self.master_weights:
            new_state["master"] = jax.tree_util.tree_unflatten(treedef, new_mw)
        return jax.tree_util.tree_unflatten(treedef, new_p), new_state
