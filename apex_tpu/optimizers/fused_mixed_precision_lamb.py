"""FusedMixedPrecisionLamb — LAMB with fp32 master state for low-precision
params and grad-scaler integration.

Parity: reference apex/optimizers/fused_mixed_precision_lamb.py:8-256
(``multi_tensor_lamb_mp`` with found_inf/inv_scale tensors, fp32 master
copies of bf16/fp16 params, step advanced only on clean iterations).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_l2norm_scale, multi_tensor_lamb_mp
from apex_tpu.optimizers._base import (
    FusedOptimizerBase,
    master_copy_tree,
    resolve_found_inf,
    zeros_like_tree,
)


class FusedMixedPrecisionLamb(FusedOptimizerBase):
    def __init__(self, lr=1e-3, step=0, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0, use_nvlamb=False,
                 reduced_precision_dtype=None):
        if amsgrad:
            raise RuntimeError("FusedMixedPrecisionLamb does not support AMSGrad.")
        self.lr = lr
        self.initial_step = step
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params):
        return {
            "step": jnp.asarray(self.initial_step, jnp.int32),
            "exp_avg": zeros_like_tree(params),
            "exp_avg_sq": zeros_like_tree(params),
            "master": master_copy_tree(params),
        }

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        lr = self.lr if lr is None else lr
        noop = resolve_found_inf(found_inf)
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        inv_scale = 1.0 / scale
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        v_leaves = treedef.flatten_up_to(state["exp_avg_sq"])
        mw_leaves = treedef.flatten_up_to(state["master"])
        gnorm, _ = multi_tensor_applier(
            multi_tensor_l2norm_scale, noop, [g_leaves], inv_scale)
        mode = 1 if self.adam_w_mode else 0
        new_p, new_m, new_v, new_mw, _ = multi_tensor_applier(
            multi_tensor_lamb_mp, noop,
            [g_leaves, p_leaves, m_leaves, v_leaves, mw_leaves],
            lr, self.betas[0], self.betas[1], self.eps, step,
            self.bias_correction, self.weight_decay, self.grad_averaging,
            mode, gnorm, self.max_grad_norm, self.use_nvlamb, noop, inv_scale)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step,
             "exp_avg": jax.tree_util.tree_unflatten(treedef, new_m),
             "exp_avg_sq": jax.tree_util.tree_unflatten(treedef, new_v),
             "master": jax.tree_util.tree_unflatten(treedef, new_mw)},
        )
