"""FusedAdagrad. Parity: reference apex/optimizers/fused_adagrad.py:5-121
(``adagrad_w_mode`` decoupled weight decay)."""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_adagrad
from apex_tpu.optimizers._base import (
    FusedOptimizerBase,
    resolve_found_inf,
    zeros_like_tree,
)


class FusedAdagrad(FusedOptimizerBase):
    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0,
                 set_grad_none=True, adagrad_w_mode=False):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "sum": zeros_like_tree(params),
        }

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        lr = self.lr if lr is None else lr
        noop = resolve_found_inf(found_inf)
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        g_leaves = [g.astype(jnp.float32) / scale for g in g_leaves]
        p_leaves = treedef.flatten_up_to(params)
        h_leaves = treedef.flatten_up_to(state["sum"])
        mode = 1 if self.adagrad_w_mode else 0
        new_p, new_h, _ = multi_tensor_applier(
            multi_tensor_adagrad, noop, [g_leaves, p_leaves, h_leaves],
            lr, self.eps, mode, self.weight_decay)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step, "sum": jax.tree_util.tree_unflatten(treedef, new_h)},
        )
