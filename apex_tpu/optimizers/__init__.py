"""apex_tpu.optimizers — fused optimizers.

Parity: reference apex/optimizers/__init__.py exports FusedAdam, FusedLAMB,
FusedSGD, FusedNovoGrad, FusedAdagrad, FusedMixedPrecisionLamb.

TPU design: each optimizer is a pure functional stepper over parameter
pytrees (``init(params) -> state``, ``step(grads, state, params) ->
(params, state)``) built on :mod:`apex_tpu.ops.multi_tensor`; the entire
update for the whole model fuses into one XLA computation — the same effect
the CUDA multi-tensor kernels achieve with batched launches. Every optimizer
also exposes ``as_gradient_transformation()`` for optax interop.
"""

from apex_tpu.optimizers.fused_adam import FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_mixed_precision_lamb import (  # noqa: F401
    FusedMixedPrecisionLamb,
)
