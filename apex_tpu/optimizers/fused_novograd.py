"""FusedNovoGrad — NovoGrad with layer-wise second moments.

Parity: reference apex/optimizers/fused_novograd.py:4-214 (``reg_inside_moment``,
``grad_averaging``, ``norm_type``, ``init_zero``).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_novograd
from apex_tpu.optimizers._base import (
    FusedOptimizerBase,
    resolve_found_inf,
    zeros_like_tree,
)


class FusedNovoGrad(FusedOptimizerBase):
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, amsgrad=False,
                 reg_inside_moment=False, grad_averaging=True, norm_type=2,
                 init_zero=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type not in (2, float("inf")):
            raise RuntimeError("FusedNovoGrad only supports l2/inf norm")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        # moment_mode 0: wd inside the moment accumulation; 1: decoupled
        # (reference fused_novograd.py maps reg_inside_moment -> moment_mode).
        self.moment_mode = 0 if reg_inside_moment else 1
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero

    def init(self, params):
        n = len(jax.tree_util.tree_leaves(params))
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": zeros_like_tree(params),
            "exp_avg_sq": jnp.zeros((n,), jnp.float32),
        }

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        lr = self.lr if lr is None else lr
        noop = resolve_found_inf(found_inf)
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        g_leaves = [g.astype(jnp.float32) / scale for g in g_leaves]
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        norm_code = 2 if self.norm_type == 2 else 0
        new_p, new_m, new_v, _ = multi_tensor_applier(
            multi_tensor_novograd, noop,
            [g_leaves, p_leaves, m_leaves, state["exp_avg_sq"]],
            lr, self.betas[0], self.betas[1], self.eps, step,
            self.bias_correction, self.weight_decay, self.grad_averaging,
            self.moment_mode, norm_code, self.init_zero)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step,
             "exp_avg": jax.tree_util.tree_unflatten(treedef, new_m),
             "exp_avg_sq": new_v},
        )
