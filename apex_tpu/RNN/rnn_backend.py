"""Stacked / bidirectional RNN runners.

Parity: reference apex/RNN/RNNBackend.py ``stackedRNN`` / ``bidirectionalRNN``.
TPU design: ``nn.scan`` over the time axis — one compiled loop, weights
held in VMEM across steps.
"""

from typing import Any, Type

import flax.linen as nn
import jax.numpy as jnp


class _ScanRunner(nn.Module):
    cell_cls: Type
    hidden_size: int
    reverse: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs):
        # xs: [seq, batch, features]
        cell = nn.scan(
            self.cell_cls,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0, out_axes=0, reverse=self.reverse,
        )(hidden_size=self.hidden_size, param_dtype=self.param_dtype)
        cell_base = getattr(self.cell_cls, "func", self.cell_cls)
        carry = cell_base.init_carry(xs.shape[1], self.hidden_size, xs.dtype)
        carry, ys = cell(carry, xs)
        return ys, carry


class StackedRNN(nn.Module):
    """num_layers cells stacked, optional dropout between layers
    (reference stackedRNN)."""

    cell_cls: Type
    hidden_size: int
    num_layers: int = 1
    dropout: float = 0.0
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs, deterministic: bool = True):
        h = xs
        final = []
        for i in range(self.num_layers):
            h, carry = _ScanRunner(self.cell_cls, self.hidden_size,
                                   param_dtype=self.param_dtype,
                                   name=f"layer_{i}")(h)
            final.append(carry)
            if self.dropout > 0 and i < self.num_layers - 1:
                h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return h, final


class BidirectionalRNN(nn.Module):
    """Forward + reverse cells, outputs concatenated
    (reference bidirectionalRNN)."""

    cell_cls: Type
    hidden_size: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs):
        fwd, cf = _ScanRunner(self.cell_cls, self.hidden_size,
                              param_dtype=self.param_dtype, name="fwd")(xs)
        bwd, cb = _ScanRunner(self.cell_cls, self.hidden_size, reverse=True,
                              param_dtype=self.param_dtype, name="bwd")(xs)
        return jnp.concatenate([fwd, bwd], axis=-1), (cf, cb)
