"""RNN cells (parity: reference apex/RNN/RNNBackend.py RNNCell + cell fns)."""

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class RNNCell(nn.Module):
    """Vanilla RNN cell with configurable nonlinearity
    (reference RNNBackend.RNNCell with gate_multiplier=1)."""

    hidden_size: int
    nonlinearity: Callable = jnp.tanh
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        h = carry
        wi = self.param("w_ih", nn.initializers.lecun_normal(),
                        (x.shape[-1], self.hidden_size), self.param_dtype)
        wh = self.param("w_hh", nn.initializers.lecun_normal(),
                        (self.hidden_size, self.hidden_size), self.param_dtype)
        b = self.param("bias", nn.initializers.zeros, (self.hidden_size,),
                       self.param_dtype)
        new_h = self.nonlinearity(
            (x @ wi + h @ wh + b).astype(jnp.float32)).astype(h.dtype)
        return new_h, new_h

    @staticmethod
    def init_carry(batch, hidden, dtype=jnp.float32):
        return jnp.zeros((batch, hidden), dtype)


class LSTMCell(nn.Module):
    """LSTM cell (reference RNNBackend gate_multiplier=4)."""

    hidden_size: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        h, c = carry
        wi = self.param("w_ih", nn.initializers.lecun_normal(),
                        (x.shape[-1], 4 * self.hidden_size), self.param_dtype)
        wh = self.param("w_hh", nn.initializers.lecun_normal(),
                        (self.hidden_size, 4 * self.hidden_size),
                        self.param_dtype)
        b = self.param("bias", nn.initializers.zeros,
                       (4 * self.hidden_size,), self.param_dtype)
        gates = (x @ wi + h @ wh + b).astype(jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        new_c = f * c.astype(jnp.float32) + i * g
        new_h = o * jnp.tanh(new_c)
        return (new_h.astype(h.dtype), new_c.astype(c.dtype)), new_h.astype(h.dtype)

    @staticmethod
    def init_carry(batch, hidden, dtype=jnp.float32):
        return (jnp.zeros((batch, hidden), dtype),
                jnp.zeros((batch, hidden), dtype))


class GRUCell(nn.Module):
    """GRU cell (reference RNNBackend gate_multiplier=3)."""

    hidden_size: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        h = carry
        wi = self.param("w_ih", nn.initializers.lecun_normal(),
                        (x.shape[-1], 3 * self.hidden_size), self.param_dtype)
        wh = self.param("w_hh", nn.initializers.lecun_normal(),
                        (self.hidden_size, 3 * self.hidden_size),
                        self.param_dtype)
        b = self.param("bias", nn.initializers.zeros,
                       (3 * self.hidden_size,), self.param_dtype)
        xi = (x @ wi + b).astype(jnp.float32)
        hh = (h @ wh).astype(jnp.float32)
        xr, xz, xn = jnp.split(xi, 3, axis=-1)
        hr, hz, hn = jnp.split(hh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        new_h = (1 - z) * n + z * h.astype(jnp.float32)
        new_h = new_h.astype(h.dtype)
        return new_h, new_h

    @staticmethod
    def init_carry(batch, hidden, dtype=jnp.float32):
        return jnp.zeros((batch, hidden), dtype)


class mLSTMCell(nn.Module):
    """Multiplicative LSTM (reference apex/RNN mLSTMRNNCell)."""

    hidden_size: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        h, c = carry
        wi = self.param("w_ih", nn.initializers.lecun_normal(),
                        (x.shape[-1], 4 * self.hidden_size), self.param_dtype)
        wh = self.param("w_hh", nn.initializers.lecun_normal(),
                        (self.hidden_size, 4 * self.hidden_size),
                        self.param_dtype)
        wmx = self.param("w_mih", nn.initializers.lecun_normal(),
                         (x.shape[-1], self.hidden_size), self.param_dtype)
        wmh = self.param("w_mhh", nn.initializers.lecun_normal(),
                         (self.hidden_size, self.hidden_size),
                         self.param_dtype)
        b = self.param("bias", nn.initializers.zeros,
                       (4 * self.hidden_size,), self.param_dtype)
        m = (x @ wmx) * (h @ wmh)
        gates = (x @ wi + m @ wh + b).astype(jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        new_c = f * c.astype(jnp.float32) + i * g
        new_h = o * jnp.tanh(new_c)
        return (new_h.astype(h.dtype), new_c.astype(c.dtype)), new_h.astype(h.dtype)

    @staticmethod
    def init_carry(batch, hidden, dtype=jnp.float32):
        return (jnp.zeros((batch, hidden), dtype),
                jnp.zeros((batch, hidden), dtype))
