"""apex_tpu.RNN — half-precision-friendly RNN re-implementations.

Parity: reference apex/RNN (models.py LSTM/GRU/ReLU/Tanh/mLSTM,
RNNBackend.py bidirectionalRNN/stackedRNN/RNNCell — deprecated in the
reference but part of its surface).

TPU design: cells are scanned with ``lax.scan`` (single compiled loop);
gates compute in fp32 with bf16 matmuls.
"""

from apex_tpu.RNN.models import GRU, LSTM, ReLU, Tanh, mLSTM  # noqa: F401
from apex_tpu.RNN.cells import GRUCell, LSTMCell, RNNCell, mLSTMCell  # noqa: F401
from apex_tpu.RNN.rnn_backend import StackedRNN, BidirectionalRNN  # noqa: F401
