"""User-facing RNN constructors.

Parity: reference apex/RNN/models.py ``LSTM/GRU/ReLU/Tanh/mLSTM`` factory
functions (bidirectional unsupported for mLSTM, like the reference).
"""

import jax.numpy as jnp

from apex_tpu.RNN.cells import GRUCell, LSTMCell, RNNCell, mLSTMCell
from apex_tpu.RNN.rnn_backend import BidirectionalRNN, StackedRNN


def _build(cell_cls, input_size, hidden_size, num_layers=1, bias=True,
           batch_first=False, dropout=0.0, bidirectional=False):
    del input_size, bias, batch_first  # inferred / always-on / seq-major
    if bidirectional:
        assert num_layers == 1, "bidirectional stacks: compose manually"
        return BidirectionalRNN(cell_cls, hidden_size)
    return StackedRNN(cell_cls, hidden_size, num_layers, dropout)


def LSTM(*args, **kwargs):
    return _build(LSTMCell, *args, **kwargs)


def GRU(*args, **kwargs):
    return _build(GRUCell, *args, **kwargs)


def ReLU(*args, **kwargs):
    import functools

    relu_cell = functools.partial(
        RNNCell, nonlinearity=lambda x: jnp.maximum(x, 0.0))
    return _build(relu_cell, *args, **kwargs)


def Tanh(*args, **kwargs):
    return _build(RNNCell, *args, **kwargs)


def mLSTM(*args, **kwargs):
    assert not kwargs.get("bidirectional", False), (
        "bidirectional mLSTM not supported (parity with reference)")
    return _build(mLSTMCell, *args, **kwargs)
