"""Split tp=1 GPT params into the per-rank tensor-parallel layout.

Bridges single-device checkpoints (e.g. the HF converters in tools/) to
the multi-chip serving/training entry points that take stacked
[tp, ...] per-rank shards (``models.tensor_parallel_generate``,
``init_params_tp`` layout). The reference has no analog — its TP
checkpoints are saved per rank.

Layout rules mirror the fused projections in
``models/transformer_lm.py`` (ParallelAttention / ParallelMLP):

- ``query_key_value``: MHA lays columns out per head as [q|k|v], so a
  contiguous split is per-head correct; GQA lays out
  [all q heads | per-group k|v], so rank r takes its q-head block AND
  its kv-group block (two-region split).
- ``dense_h_to_4h``: gelu is a plain column split; swiglu is fused
  [gate | up], so each half splits separately (two-region).
- ``dense`` / ``dense_4h_to_h`` (row-parallel): split the input dim
  (second-to-last axis); row biases are replicated (added once after
  the tp psum).
- ``word_embeddings``: vocab rows; ``lm_head``: vocab columns.
- everything else (layernorms, position embeddings) replicates.

Negative axes keep the rules valid for ``scan_layers`` param stacks
(leading [num_layers] dim).
"""

import jax
import jax.numpy as jnp


def _split_contiguous(x, tp, axis):
    return jnp.stack(jnp.split(x, tp, axis=axis))


def _split_two_region(x, tp, size_a, axis):
    """Split [region_a | region_b] along ``axis``: rank r gets its 1/tp
    slice of each region, concatenated."""
    a, b_ = jnp.split(x, [size_a], axis=axis)
    a_shards = jnp.split(a, tp, axis=axis)
    b_shards = jnp.split(b_, tp, axis=axis)
    return jnp.stack([jnp.concatenate([a_shards[r], b_shards[r]], axis=axis)
                      for r in range(tp)])


def _replicate(x, tp):
    return jnp.broadcast_to(x[None], (tp,) + x.shape)


# Module names whose >=2-D params legitimately replicate across tp ranks
# (everything else with a matrix shape must match a split rule or the
# split fails loudly — a silently replicated projection would produce
# shards that are wrong or shape-mismatched only at apply time).
_REPLICATED_MODULES = frozenset({
    "position_embeddings", "input_layernorm", "post_attention_layernorm",
    "final_layernorm",
    # ViT (models/vit.py): embed/classifier touch only the replicated
    # residual dim; the transformer body splits by the rules above
    "patch_embed", "cls_token", "classifier",
})


def _path_names(path):
    """The module/param name components of a pytree path (DictKey keys and
    flax FrozenDict keys), robust against keystr formatting (ADVICE r2:
    substring matching on the rendered keystr is brittle)."""
    names = []
    for entry in path:
        key = getattr(entry, "key", None)
        if isinstance(key, str):
            names.append(key)
    return names


def _dense_tp_rule(cfg, tp):
    """The per-leaf dense-GPT tp-split rule (module doc): returns a
    ``rule(path, leaf) -> [tp, ...]`` closure after validating
    divisibility. Shared by ``split_params_for_tp`` and the MoE loader
    (``models.reshard``), which handles expert/router leaves itself."""
    heads, groups = cfg.num_attention_heads, cfg.query_groups
    kv = cfg.kv_channels
    for name, n in (("num_attention_heads", heads),
                    ("query_groups", groups),
                    ("ffn_size", cfg.ffn_size)):
        if n % tp:
            raise ValueError(f"{name} ({n}) is not divisible by tp ({tp})")

    def rule(path, leaf):
        names = set(_path_names(path))
        if (names & {"word_embeddings", "lm_head", "lm_head_bias"}
                and cfg.vocab_size % tp):
            # checked lazily: vocab-less models (ViT) carry a dummy
            # vocab_size and no vocab-sharded leaves
            raise ValueError(f"vocab_size ({cfg.vocab_size}) is not "
                             f"divisible by tp ({tp})")
        if "query_key_value" in names:
            if groups == heads:
                return _split_contiguous(leaf, tp, -1)
            return _split_two_region(leaf, tp, heads * kv, -1)
        if "dense_h_to_4h" in names:
            if cfg.activation in ("swiglu", "geglu"):
                return _split_two_region(leaf, tp, cfg.ffn_size, -1)
            return _split_contiguous(leaf, tp, -1)
        if ("dense_4h_to_h" in names
                or ("dense" in names and "self_attention" in names)):
            if leaf.ndim >= 2 and "weight" in names:
                return _split_contiguous(leaf, tp, -2)
            return _replicate(leaf, tp)  # row bias: added once post-psum
        if "word_embeddings" in names:
            return _split_contiguous(leaf, tp, -2)
        if "lm_head" in names or "lm_head_bias" in names:
            return _split_contiguous(leaf, tp, -1)
        if leaf.ndim >= 2 and not (names & _REPLICATED_MODULES):
            raise ValueError(
                f"split_params_for_tp: unrecognized weight matrix at "
                f"{jax.tree_util.keystr(path)} (shape {leaf.shape}) — "
                f"refusing to silently replicate; add a split rule")
        return _replicate(leaf, tp)

    return rule


# -- T5 family ---------------------------------------------------------------

# column-parallel (split output columns, axis -1) / row-parallel (split
# input rows, axis -2) module names in models/t5.py
_T5_COLUMN = frozenset({"q", "k", "v", "wi", "wi_0", "wi_1"})
_T5_ROW = frozenset({"o", "wo"})
_T5_REPLICATED = frozenset({
    "self_attn_norm", "cross_attn_norm", "ffn_norm", "final_norm",
    "relative_bias",  # full [buckets, heads] table; module slices per rank
})


def split_t5_params_for_tp(cfg, params, tp: int):
    """Stacked [tp, ...] layout for a tp=1 T5Model param tree: per-head
    column splits for q/k/v and the (gated) FFN up-projections, row
    splits for o/wo, vocab rows for the shared embedding, vocab columns
    for an untied head; the relative-bias table replicates (the module
    reads its head slice by rank). Fails loudly on unknown matrices."""
    for name, n in (("num_heads", cfg.num_heads), ("d_ff", cfg.d_ff),
                    ("vocab_size", cfg.vocab_size)):
        if n % tp:
            raise ValueError(f"{name} ({n}) is not divisible by tp ({tp})")
    if tp == 1:
        return jax.tree_util.tree_map(lambda a: a[None], params)

    def rule(path, leaf):
        names = set(_path_names(path))
        if names & _T5_COLUMN:
            return _split_contiguous(leaf, tp, -1)
        if names & _T5_ROW:
            return _split_contiguous(leaf, tp, -2)
        if "shared" in names:
            return _split_contiguous(leaf, tp, -2)
        if "lm_head" in names:
            return _split_contiguous(leaf, tp, -1)
        if leaf.ndim >= 2 and not (names & _T5_REPLICATED):
            raise ValueError(
                f"split_t5_params_for_tp: unrecognized weight matrix at "
                f"{jax.tree_util.keystr(path)} (shape {leaf.shape}) — "
                f"refusing to silently replicate; add a split rule")
        return _replicate(leaf, tp)

    return jax.tree_util.tree_map_with_path(rule, params)


# -- MLA / DeepSeek family ---------------------------------------------------

_MLA_COLUMN = frozenset({"q_b", "kv_b"})  # per-head expansions
_MLA_ROW = frozenset({"o", "down"})
_MLA_REPLICATED = frozenset({
    "q_a", "kv_a",            # shared latent projections ride every rank
    "q_a_norm", "kv_a_norm", "input_norm", "post_attn_norm", "final_norm",
})


def split_mla_params_for_tp(cfg, params, tp: int):
    """Stacked [tp, ...] layout for a tp=1 DeepseekModel tree: per-head
    column splits for the latent expansions (q_b/kv_b) and the fused
    gate_up, row splits for o/down, vocab rows for the embedding, vocab
    columns for the head; the LATENT projections and their norms
    replicate (models/mla.py TP design). Packed [gate | up] projections
    (dense mlp AND the shared expert, whose half-width is
    n_shared_experts * moe_intermediate_size) split two-region at the
    leaf's own midpoint. MoE layers: the router gate replicates (routing
    must agree on every tp rank — SwitchMLP's copy/reduce pairing
    assumes it), expert w1 is per-expert packed [gate | up] (two-region
    on the last axis), expert w2 row-splits — matching ExpertMLP's
    ffn/tp local layout."""
    for name, n in (("num_heads", cfg.num_heads),
                    ("ffn_hidden_size", cfg.ffn_hidden_size),
                    ("vocab_size", cfg.vocab_size)):
        if n % tp:
            raise ValueError(f"{name} ({n}) is not divisible by tp ({tp})")
    if getattr(cfg, "n_routed_experts", None) and \
            cfg.moe_intermediate_size % tp:
        raise ValueError(f"moe_intermediate_size "
                         f"({cfg.moe_intermediate_size}) is not divisible "
                         f"by tp ({tp})")
    if tp == 1:
        return jax.tree_util.tree_map(lambda a: a[None], params)

    def split_packed_gate_up(path, leaf):
        half = leaf.shape[-1] // 2
        if leaf.shape[-1] % 2 or half % tp:
            raise ValueError(
                f"split_mla_params_for_tp: packed [gate | up] leaf at "
                f"{jax.tree_util.keystr(path)} (shape {leaf.shape}) has "
                f"half-width {half}, not divisible by tp ({tp})")
        return _split_two_region(leaf, tp, half, -1)

    def rule(path, leaf):
        names = set(_path_names(path))
        if "gate_up" in names:
            return split_packed_gate_up(path, leaf)
        if "experts" in names:
            if "w1" in names:
                return split_packed_gate_up(path, leaf)
            if "w2" in names:
                return _split_contiguous(leaf, tp, -2)
            raise ValueError(
                f"split_mla_params_for_tp: unrecognized expert param at "
                f"{jax.tree_util.keystr(path)} (shape {leaf.shape})")
        if "gate_weight" in names:  # MoE router: replicated
            return _replicate(leaf, tp)
        if names & _MLA_COLUMN:
            return _split_contiguous(leaf, tp, -1)
        if names & _MLA_ROW:
            return _split_contiguous(leaf, tp, -2)
        if "embed_tokens" in names:
            return _split_contiguous(leaf, tp, -2)
        if "lm_head" in names:
            return _split_contiguous(leaf, tp, -1)
        if leaf.ndim >= 2 and not (names & _MLA_REPLICATED):
            raise ValueError(
                f"split_mla_params_for_tp: unrecognized weight matrix at "
                f"{jax.tree_util.keystr(path)} (shape {leaf.shape})")
        return _replicate(leaf, tp)

    return jax.tree_util.tree_map_with_path(rule, params)


def split_params_for_tp(cfg, params, tp: int):
    """Return the stacked [tp, ...] pytree for a tp=1 GPTModel param
    tree (see module doc). Validates divisibility of heads/groups/ffn/
    vocab by ``tp``; raises on configs/leaves outside the GPT layout it
    knows (MoE expert/router weights have their own ep layout — use
    ``models.reshard.load_moe_checkpoint_for_ep``)."""
    if getattr(cfg, "num_moe_experts", None):
        raise ValueError(
            "split_params_for_tp handles dense GPT checkpoints only; MoE "
            "expert/router weights need the ep-sharded layout "
            "(models.reshard.load_moe_checkpoint_for_ep), not a tp split")
    if tp == 1:
        return jax.tree_util.tree_map(lambda a: a[None], params)
    return jax.tree_util.tree_map_with_path(_dense_tp_rule(cfg, tp),
                                            params)
