"""ResNet family (NHWC, TPU-native) for the ImageNet example + bench.

Parity: the reference's ImageNet example uses torchvision ResNet-50
(examples/imagenet/main_amp.py); the model itself is standard He et al.
Bottleneck ResNet. TPU design: channels-last (NHWC) layout feeding the
MXU's conv path, BatchNorm swappable for SyncBatchNorm (the reference's
``--sync_bn`` flag + convert_syncbn_model), bf16 compute with fp32 norms.
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence, Type

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 (self.strides, self.strides),
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides),
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Type
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    sync_bn: bool = False
    bn_axis_name: Optional[str] = "dp"
    train: bool = True

    @nn.compact
    def __call__(self, x, train: Optional[bool] = None):
        train = self.train if train is None else train
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        if self.sync_bn:
            norm = partial(SyncBatchNorm, use_running_average=not train,
                           axis_name=self.bn_axis_name, momentum=0.9,
                           epsilon=1e-5, dtype=self.dtype)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
