"""Autoregressive generation with KV-cache decoding.

No reference counterpart (NVIDIA Apex is training-only); this completes
the model family with a serving-shaped path: prefill the cache in one
pass over the prompt, then a jitted ``lax.scan`` of single-token steps —
static shapes throughout, cache carried as scan state. Compiled step
functions are cached per (model, shape, sampling-config), so a serving
loop pays compile cost once.

    model = GPTModel(cfg, decode=True)
    out = generate(model, params, prompt_tokens, max_new_tokens=64,
                   temperature=0.8, top_k=40, rng=jax.random.PRNGKey(0))
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.tensor_parallel.mappings import (
    gather_from_tensor_model_parallel_region,
)


def sample_logits(logits, rng, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Sample token ids from [batch, vocab] logits.

    ``temperature=0`` is greedy argmax. top-k keeps the k highest logits
    (clamped to the vocab size); top-p (nucleus) keeps the smallest
    prefix of the sorted distribution with cumulative probability >= p.
    Filters compose (k first, then p).
    """
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -min(top_k, logits.shape[-1])]
        logits = jnp.where(logits < kth[:, None], -jnp.inf, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix reaching mass p: a token stays if the
        # mass *before* it is < p (the top token always stays)
        keep = (cum - probs) < top_p
        threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                            axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def _full_vocab(logits):
    """Gather vocab-parallel logits over tp (no-op when tp is unbound /
    size 1) so sampling sees the full vocabulary."""
    return gather_from_tensor_model_parallel_region(logits)


@functools.lru_cache(maxsize=32)
def _compiled(model, plen, max_new_tokens, temperature, top_k, top_p,
              eos_token_id, pad_token_id):
    """jitted prefill + scan-decode, cached per model/config (shape
    specialization is jit's own cache)."""

    @jax.jit
    def prefill(params, cache, tokens):
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tokens,
            jnp.arange(plen)[None, :], mutable=["cache"])
        return mut["cache"], _full_vocab(logits[:, -1])

    def step(params, carry, _):
        cache, logits, t, key, done = carry
        b = logits.shape[0]
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, temperature, top_k, top_p)
        nxt = jnp.where(done, pad_token_id, nxt)
        if eos_token_id is not None:
            done = done | (nxt == eos_token_id)
        pos = jnp.broadcast_to(t[None, None], (b, 1))
        new_logits, mut = model.apply(
            {"params": params, "cache": cache}, nxt[:, None], pos,
            mutable=["cache"])
        return ((mut["cache"], _full_vocab(new_logits[:, -1]), t + 1, key,
                 done), nxt)

    @jax.jit
    def decode_all(params, init):
        return jax.lax.scan(functools.partial(step, params), init, None,
                            length=max_new_tokens)

    return prefill, decode_all


def init_cache(model, batch_size: int, dtype_token=jnp.int32):
    """Zeroed KV cache for ``model`` (built with decode=True) without
    materializing any parameters (shape-only trace)."""
    dummy = jnp.zeros((batch_size, 1), dtype_token)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy))["cache"]
    return jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), shapes)


def generate(model, params, prompt_tokens, max_new_tokens: int, *,
             rng=None, temperature: float = 1.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0):
    """Prefill + scan-decode. Returns [batch, prompt + max_new_tokens]
    (generated positions after an eos are ``pad_token_id``).

    ``model`` must be constructed with ``decode=True``; the prompt plus
    generated tokens must fit ``max_position_embeddings``. Greedy when
    ``rng`` is None or ``temperature == 0``. Prompts must be unpadded
    (decode mode rejects attention masks — left-trim or batch by
    length). This host-level loop drives a single-device (tp=1) model;
    for tensor-parallel decoding build your own step inside shard_map
    from ``model.apply`` + ``sample_logits`` (the compiled step already
    gathers vocab-parallel logits over tp when the axis is bound).
    """
    if not getattr(model, "decode", False):
        raise ValueError("generate() needs a model built with decode=True")
    from apex_tpu.transformer.parallel_state import (
        get_tensor_model_parallel_world_size,
    )

    if get_tensor_model_parallel_world_size() > 1:
        raise NotImplementedError(
            "generate() drives a tp=1 model; for tensor parallelism run "
            "the decode step inside shard_map (see docstring)")
    cfg = model.config
    b, plen = prompt_tokens.shape
    if plen + max_new_tokens > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt ({plen}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings ({cfg.max_position_embeddings})")
    if rng is None:
        temperature = 0.0
        rng = jax.random.PRNGKey(0)

    prefill, decode_all = _compiled(
        model, plen, max_new_tokens, float(temperature), top_k, top_p,
        eos_token_id, pad_token_id)
    cache = init_cache(model, b, prompt_tokens.dtype)
    cache, last_logits = prefill(params, cache, prompt_tokens)
    init = (cache, last_logits, jnp.asarray(plen, jnp.int32), rng,
            jnp.zeros((b,), bool))
    _, out = decode_all(params, init)  # [max_new, b]
    return jnp.concatenate([prompt_tokens, out.T], axis=1)
