"""Autoregressive generation with KV-cache decoding.

No reference counterpart (NVIDIA Apex is training-only); this completes
the model family with a serving-shaped path: prefill the cache in one
pass over the prompt, then a jitted ``lax.scan`` of single-token steps —
static shapes throughout, cache carried as scan state. Compiled step
functions are cached per (model, shape, sampling-config), so a serving
loop pays compile cost once.

The primitives are the pure module-level :func:`prefill` /
:func:`decode_step` pair; :func:`generate` is a thin jit+scan wrapper
over them, and ``apex_tpu.serving.ServeEngine`` vmaps the same pair
over cache slots for continuous batching.

    model = GPTModel(cfg, decode=True)
    out = generate(model, params, prompt_tokens, max_new_tokens=64,
                   temperature=0.8, top_k=40, rng=jax.random.PRNGKey(0))
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.tensor_parallel.mappings import (
    gather_from_tensor_model_parallel_region,
)


def sample_logits(logits, rng, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Sample token ids from [batch, vocab] logits.

    ``temperature=0`` is greedy argmax. top-k keeps the k highest logits
    (clamped to the vocab size); top-p (nucleus) keeps the smallest
    prefix of the sorted distribution with cumulative probability >= p.
    Filters compose (k first, then p).
    """
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -min(top_k, logits.shape[-1])]
        logits = jnp.where(logits < kth[:, None], -jnp.inf, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix reaching mass p: a token stays if the
        # mass *before* it is < p (the top token always stays)
        keep = (cum - probs) < top_p
        threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                            axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def _full_vocab(logits):
    """Gather vocab-parallel logits over tp (no-op when tp is unbound /
    size 1) so sampling sees the full vocabulary."""
    return gather_from_tensor_model_parallel_region(logits)


def prefill(model, params, cache, tokens, positions, *,
            full_logits=False):
    """Run one prompt chunk through the KV cache (pure, trace-friendly).

    The reusable prefill building block: every compiled entry point
    here (:func:`generate`'s jitted prefill, the serving engine's
    per-slot AOT prefill) is this function under a ``jit``/``vmap`` of
    the caller's choosing. ``positions`` is ``[b, s]`` (or ``[1, s]``)
    absolute positions of ``tokens``. Returns ``(new_cache, logits)``
    where ``logits`` is the full-vocabulary (tp-gathered) logits at the
    LAST position ``[b, vocab]`` — or at every position ``[b, s,
    vocab]`` with ``full_logits=True`` (a right-padded serving prefill
    picks its own true-length position)."""
    logits, mut = model.apply({"params": params, "cache": cache},
                              tokens, positions, mutable=["cache"])
    if full_logits:
        return mut["cache"], _full_vocab(logits)
    return mut["cache"], _full_vocab(logits[:, -1])


def decode_step(model, params, cache, tokens, positions):
    """One incremental decode forward over the KV cache (pure).

    ``tokens`` is ``[b, s]`` (s=1 in the serving hot loop), ``positions``
    the matching absolute positions. Returns ``(new_cache, logits)``
    with full-vocabulary logits at the last position ``[b, vocab]`` —
    the sampling input for the next token. :func:`generate`'s scan body
    and the serving engine's AOT decode step both consume this."""
    logits, mut = model.apply({"params": params, "cache": cache},
                              tokens, positions, mutable=["cache"])
    return mut["cache"], _full_vocab(logits[:, -1])


@functools.lru_cache(maxsize=32)
def _compiled(model, plen, max_new_tokens, temperature, top_k, top_p,
              eos_token_id, pad_token_id, prefix_len=0):
    """jitted prefill + scan-decode, cached per model/config (shape
    specialization is jit's own cache). ``prefix_len`` > 0 means the
    cache already holds a shared prefilled prefix: the prompt chunk and
    the decode steps run at offset absolute positions. Thin jit/scan
    shells over the reusable :func:`prefill` / :func:`decode_step`."""

    @jax.jit
    def prefill_fn(params, cache, tokens):
        return prefill(model, params, cache, tokens,
                       (prefix_len + jnp.arange(plen))[None, :])

    def step(params, carry, _):
        cache, logits, t, key, done = carry
        b = logits.shape[0]
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, temperature, top_k, top_p)
        nxt = jnp.where(done, pad_token_id, nxt)
        if eos_token_id is not None:
            done = done | (nxt == eos_token_id)
        pos = jnp.broadcast_to(t[None, None], (b, 1))
        cache, new_logits = decode_step(model, params, cache,
                                        nxt[:, None], pos)
        return ((cache, new_logits, t + 1, key, done), nxt)

    @jax.jit
    def decode_all(params, init):
        return jax.lax.scan(functools.partial(step, params), init, None,
                            length=max_new_tokens)

    return prefill_fn, decode_all


def init_cache(model, batch_size: int, dtype_token=jnp.int32):
    """Zeroed KV cache for ``model`` (built with decode=True) without
    materializing any parameters (shape-only trace)."""
    dummy = jnp.zeros((batch_size, 1), dtype_token)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy))["cache"]
    return jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), shapes)


@functools.lru_cache(maxsize=16)
def _compiled_beam(model, plen, max_new_tokens, num_beams, length_penalty,
                   eos_token_id, pad_token_id):
    k = num_beams

    @jax.jit
    def run(params, cache, prompt_tokens):
        b = prompt_tokens.shape[0]
        logits, mut = model.apply(
            {"params": params, "cache": cache}, prompt_tokens,
            jnp.arange(plen)[None, :], mutable=["cache"])
        logp0 = jax.nn.log_softmax(
            _full_vocab(logits[:, -1]).astype(jnp.float32))  # [b, v]
        vocab = logp0.shape[-1]

        # Tile the cache per beam: cached K/V are [s, b, g, d] with batch
        # at axis 1; cache_index is a scalar.
        cache = jax.tree_util.tree_map(
            lambda x: x if x.ndim == 0 else jnp.repeat(x, k, axis=1),
            mut["cache"])

        scores, tok0 = jax.lax.top_k(logp0, k)            # [b, k]
        done = (jnp.zeros((b, k), bool) if eos_token_id is None
                else tok0 == eos_token_id)
        lengths = jnp.ones((b, k), jnp.int32)
        seqs = jnp.zeros((b * k, max_new_tokens), jnp.int32)
        seqs = seqs.at[:, 0].set(tok0.reshape(b * k))

        def step(carry, i):
            cache, scores, done, lengths, seqs = carry
            prev = seqs[jnp.arange(b * k), i - 1]
            pos = jnp.full((b * k, 1), plen + i - 1, jnp.int32)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, prev[:, None], pos,
                mutable=["cache"])
            cache = mut["cache"]
            logp = jax.nn.log_softmax(
                _full_vocab(logits[:, 0]).astype(jnp.float32)
            ).reshape(b, k, vocab)
            # frozen beams extend only with pad, at zero cost
            frozen = jnp.full((vocab,), -jnp.inf).at[pad_token_id].set(0.0)
            logp = jnp.where(done[:, :, None], frozen[None, None, :], logp)
            total = scores[:, :, None] + logp             # [b, k, v]
            scores, flat = jax.lax.top_k(total.reshape(b, k * vocab), k)
            beam_idx = flat // vocab                      # [b, k]
            tok = flat % vocab
            gather = (jnp.arange(b)[:, None] * k + beam_idx).reshape(b * k)
            cache = jax.tree_util.tree_map(
                lambda x: x if x.ndim == 0 else jnp.take(x, gather, axis=1),
                cache)
            done = jnp.take_along_axis(done, beam_idx, axis=1)
            lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
            lengths = lengths + (~done).astype(jnp.int32)
            seqs = jnp.take(seqs, gather, axis=0)
            seqs = seqs.at[:, i].set(tok.reshape(b * k))
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
            return (cache, scores, done, lengths, seqs), None

        if max_new_tokens > 1:
            (cache, scores, done, lengths, seqs), _ = jax.lax.scan(
                step, (cache, scores, done, lengths, seqs),
                jnp.arange(1, max_new_tokens))
        adjusted = scores / (lengths.astype(jnp.float32) ** length_penalty)
        best = jnp.argmax(adjusted, axis=-1)              # [b]
        rows = jnp.arange(b) * k + best
        return jnp.take(seqs, rows, axis=0), jnp.take_along_axis(
            adjusted, best[:, None], axis=1)[:, 0]

    return run


def beam_search(model, params, prompt_tokens, max_new_tokens: int,
                num_beams: int = 4, *, length_penalty: float = 1.0,
                eos_token_id: Optional[int] = None, pad_token_id: int = 0):
    """Beam-search decoding with a KV cache per beam.

    Returns ([batch, prompt + max_new_tokens] tokens, [batch] scores):
    the highest-scoring beam per row, score = sum of token log-probs /
    length**length_penalty (length counts tokens up to and including
    eos). Beams share the prompt prefill; the cache is tiled to
    batch*num_beams and reordered along its batch axis as beams are
    reselected each step; finished beams are frozen (extend with pad at
    zero cost). tp=1, like :func:`generate`.
    """
    from apex_tpu.transformer.parallel_state import (
        get_tensor_model_parallel_world_size,
    )

    if get_tensor_model_parallel_world_size() > 1:
        raise NotImplementedError(
            "beam_search() drives a tp=1 model; use "
            "tensor_parallel_beam_search()")
    _validate_decode("beam_search", model, prompt_tokens, max_new_tokens)
    b, plen = prompt_tokens.shape
    run = _compiled_beam(model, plen, max_new_tokens, num_beams,
                         float(length_penalty), eos_token_id, pad_token_id)
    cache = init_cache(model, b, prompt_tokens.dtype)
    best_seqs, best_scores = run(params, cache, prompt_tokens)
    return jnp.concatenate([prompt_tokens, best_seqs], axis=1), best_scores


def tensor_parallel_beam_search(model, stacked_params, prompt_tokens,
                                max_new_tokens: int, num_beams: int = 4, *,
                                mesh=None, length_penalty: float = 1.0,
                                eos_token_id: Optional[int] = None,
                                pad_token_id: int = 0):
    """Beam search under tensor parallelism (same shard_map pattern as
    :func:`tensor_parallel_generate`). The beam body is rank-local
    except the vocab gather: log-probs are identical on every tp rank
    after `_full_vocab`, so each rank performs the same beam reordering
    on its own KV shard (cached K/V keep batch*beams at axis 1, which is
    never tp-sharded)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    _validate_decode("tensor_parallel_beam_search", model, prompt_tokens,
                     max_new_tokens)
    mesh = mesh or parallel_state.get_mesh()
    b, plen = prompt_tokens.shape
    run = _compiled_beam(model, plen, max_new_tokens, num_beams,
                         float(length_penalty), eos_token_id, pad_token_id)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("tp"), P()), out_specs=(P(), P()),
                       check_vma=False)
    def go(sp, toks):
        params = jax.tree_util.tree_map(lambda a: a[0], sp)
        cache = init_cache(model, b, toks.dtype)
        return run(params, cache, toks)

    best_seqs, best_scores = go(stacked_params, prompt_tokens)
    return jnp.concatenate([prompt_tokens, best_seqs], axis=1), best_scores


def _validate_decode(fn_name, model, prompt_tokens, max_new_tokens,
                     extra=0, extra_label="draft window"):
    """Shared decode-entry validation (all public entry points;
    speculative_generate passes its draft-window headroom and
    prefix-cached generate() its prefix length via ``extra`` so errors
    report the caller's own numbers)."""
    if not getattr(model, "decode", False):
        raise ValueError(f"{fn_name}() needs a model built with "
                         f"decode=True")
    plen = prompt_tokens.shape[1]
    limit = model.config.max_position_embeddings
    if plen + max_new_tokens + extra > limit:
        extra_s = f" + {extra_label} ({extra})" if extra else ""
        raise ValueError(
            f"prompt ({plen}) + max_new_tokens ({max_new_tokens})"
            f"{extra_s} exceeds max_position_embeddings ({limit})")


def _prep_decode(fn_name, model, prompt_tokens, max_new_tokens, rng,
                 temperature, top_k, top_p, eos_token_id, pad_token_id,
                 prefix_len=0):
    """Shared validation + compile for generate()/tensor_parallel_generate:
    returns (prefill, decode_all, rng)."""
    _validate_decode(fn_name, model, prompt_tokens, max_new_tokens,
                     extra=prefix_len, extra_label="prefix")
    plen = prompt_tokens.shape[1]
    if rng is None:
        temperature = 0.0
        rng = jax.random.PRNGKey(0)
    prefill, decode_all = _compiled(
        model, plen, max_new_tokens, float(temperature), top_k, top_p,
        eos_token_id, pad_token_id, prefix_len)
    return prefill, decode_all, rng


def _prefill_and_decode(prefill, decode_all, model, params, prompt_tokens,
                        rng, prefix_cache=None, prefix_len=0):
    """One prefill + scan-decode pass; returns the generated [b, new]."""
    b, plen = prompt_tokens.shape
    cache = (init_cache(model, b, prompt_tokens.dtype)
             if prefix_cache is None else prefix_cache)
    cache, last_logits = prefill(params, cache, prompt_tokens)
    init = (cache, last_logits,
            jnp.asarray(prefix_len + plen, jnp.int32), rng,
            jnp.zeros((b,), bool))
    _, out = decode_all(params, init)  # [max_new, b]
    return out.T


@functools.lru_cache(maxsize=16)
def _compiled_prefix(model, plen):
    """Jitted prefix forward, cached per (model, prefix length) like
    every other compiled entry point here — a serving loop prefilling
    many same-shape system prompts pays the compile once."""

    @jax.jit
    def run(params, cache, tokens):
        _, mut = model.apply({"params": params, "cache": cache}, tokens,
                             jnp.arange(plen)[None, :],
                             mutable=["cache"])
        return mut["cache"]

    return run


def prefill_prefix(model, params, prefix_tokens):
    """Prefill a SHARED prompt prefix once and return an opaque
    ``(cache, prefix_len)`` state for ``generate(prefix_state=...)`` —
    the serving prompt-cache pattern: one system prompt, many
    continuations. The prefix forward runs exactly once; every
    continuation then prefills only its suffix at offset positions.

    The returned cache may be reused across any number of generate()
    calls (nothing donates it), and a batch-1 prefix broadcasts to any
    continuation batch size."""
    from apex_tpu.transformer.parallel_state import (
        get_tensor_model_parallel_world_size,
    )

    if get_tensor_model_parallel_world_size() > 1:
        raise NotImplementedError(
            "prefill_prefix() drives a tp=1 model (the tensor-parallel "
            "serving loop has no prefix-cache path yet)")
    if not getattr(model, "decode", False):
        raise ValueError("prefill_prefix() needs a model built with "
                         "decode=True")
    b, plen = prefix_tokens.shape
    limit = model.config.max_position_embeddings
    if plen >= limit:
        raise ValueError(f"prefix ({plen}) leaves no room under "
                         f"max_position_embeddings ({limit})")
    cache = init_cache(model, b, prefix_tokens.dtype)
    run = _compiled_prefix(model, plen)
    return run(params, cache, prefix_tokens), plen


def _broadcast_prefix_cache(cache, b):
    """A batch-1 prefix cache serves a batch-b continuation: K/V
    buffers broadcast along their batch axis — axis ndim-3, which
    handles both the plain [T, b, g, d] layout and scan_layers'
    layer-stacked [L, T, b, g, d]. Scalar leaves (cache_index) pass
    through."""
    def fix(path, leaf):
        names = [getattr(e, "key", None) for e in path]
        if (names and str(names[-1]).startswith("cached_")
                and leaf.ndim >= 3):
            ax = leaf.ndim - 3
            if leaf.shape[ax] == b:
                return leaf
            if leaf.shape[ax] != 1:
                raise ValueError(
                    f"prefix cache batch ({leaf.shape[ax]}) != prompt "
                    f"batch ({b}); only batch-1 prefixes broadcast")
            return jnp.broadcast_to(
                leaf, leaf.shape[:ax] + (b,) + leaf.shape[ax + 1:])
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def generate(model, params, prompt_tokens, max_new_tokens: int, *,
             rng=None, temperature: float = 1.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             prefix_state=None):
    """Prefill + scan-decode. Returns [batch, prompt + max_new_tokens]
    (generated positions after an eos are ``pad_token_id``).

    ``model`` must be constructed with ``decode=True``; the prompt plus
    generated tokens must fit ``max_position_embeddings``. Greedy when
    ``rng`` is None or ``temperature == 0``. Prompts must be unpadded
    (decode mode rejects attention masks — left-trim or batch by
    length). This host-level loop drives a single-device (tp=1) model;
    for tensor-parallel decoding use :func:`tensor_parallel_generate`.

    ``prefix_state`` (from :func:`prefill_prefix`): a shared prefilled
    prompt prefix — ``prompt_tokens`` is then the per-request SUFFIX,
    prefilled at offset positions into (a batch-broadcast copy of) the
    prefix cache; output is [batch, suffix + max_new_tokens] (the
    prefix tokens belong to the caller). Token-exact vs prefilling the
    concatenated prompt from scratch.
    """
    from apex_tpu.transformer.parallel_state import (
        get_tensor_model_parallel_world_size,
    )

    if get_tensor_model_parallel_world_size() > 1:
        raise NotImplementedError(
            "generate() drives a tp=1 model; use "
            "tensor_parallel_generate() (the same prefill + scan loop "
            "inside shard_map over the 'tp' axis)")
    prefix_cache, prefix_len = (None, 0)
    if prefix_state is not None:
        prefix_cache, prefix_len = prefix_state
        prefix_cache = _broadcast_prefix_cache(prefix_cache,
                                               prompt_tokens.shape[0])
    prefill, decode_all, rng = _prep_decode(
        "generate", model, prompt_tokens, max_new_tokens, rng, temperature,
        top_k, top_p, eos_token_id, pad_token_id, prefix_len)
    out = _prefill_and_decode(prefill, decode_all, model, params,
                              prompt_tokens, rng, prefix_cache,
                              prefix_len)
    return jnp.concatenate([prompt_tokens, out], axis=1)


def verify_step(model, params, cache, chunk, positions):
    """One speculative-verification forward (pure, trace-friendly).

    ``chunk`` is ``[b, k+1]`` — the last emitted token followed by the
    draft's k proposals — and ``positions`` the matching absolute
    positions. The target runs the whole window in ONE chunked forward
    over its KV cache; the returned greedy verdicts ``v`` are ``[b,
    k+1] i32`` with ``v[:, i]`` the target argmax for the position
    after ``chunk[:, i]`` — the acceptance comparison's right-hand
    side. Returns ``(new_cache, v, logits)`` (full-vocabulary logits
    ``[b, k+1, vocab]``, the fused sampling/quarantine epilogue's
    input). The split exists so :func:`speculative_generate` and the
    serving engine's in-graph speculative decode run the SAME
    verification body (tests pin both against plain greedy)."""
    logits, mut = model.apply({"params": params, "cache": cache},
                              chunk, positions, mutable=["cache"])
    full = _full_vocab(logits)
    v = jnp.argmax(full, axis=-1).astype(jnp.int32)
    return mut["cache"], v, full


def _set_cache_index(cache, value):
    """Roll every layer's scalar ``cache_index`` to ``value`` (leaves
    beyond the index stay resident but masked — the decode attention
    masks by absolute position, so a rollback is just the index)."""
    def fix(path, leaf):
        names = [getattr(e, "key", None) for e in path]
        if names and names[-1] == "cache_index":
            return jnp.full_like(leaf, value)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.lru_cache(maxsize=16)
def _compiled_speculative(target, draft, plen, max_new, k, eos_token_id,
                          pad_token_id):
    """Jitted speculative-decode engine (greedy): per round the draft
    proposes ``k`` tokens via its own KV cache, the target verifies all
    of them in ONE (k+1)-token chunk forward, and the longest matching
    prefix plus one target token (correction on mismatch, bonus on full
    accept) is emitted. Output is token-exact vs target-alone greedy:
    every emitted token is an argmax of target logits over the same
    prefix. Batch rows accept the round-wise MINIMUM across the batch —
    still exact per row (a shorter accepted prefix is still a verified
    prefix), just less speedup on skewed batches."""

    @jax.jit
    def run(tparams, dparams, tcache, dcache, prompt):
        b = prompt.shape[0]
        pos = jnp.arange(plen)[None, :]
        tlg, tmut = target.apply({"params": tparams, "cache": tcache},
                                 prompt, pos, mutable=["cache"])
        _, dmut = draft.apply({"params": dparams, "cache": dcache},
                              prompt, pos, mutable=["cache"])
        tcache, dcache = tmut["cache"], dmut["cache"]
        last = jnp.argmax(_full_vocab(tlg[:, -1]), -1).astype(jnp.int32)

        buf_w = max_new + k + 1
        out = jnp.full((b, buf_w), pad_token_id, jnp.int32)
        out = out.at[:, 0].set(last)
        n0 = jnp.asarray(1, jnp.int32)

        def cond(c):
            return c[0] < max_new

        def body(c):
            n, last, out, tcache, dcache = c
            # absolute position of `last` — passed EXPLICITLY on every
            # decode forward: learned-position models embed by
            # position_ids (the arange default only suits prefill), and
            # rope models accept the same explicit positions
            t0 = plen + n - 1

            # draft: k proposals + one cache-completion feed of d_k, so
            # the draft cache never has a hole after a full accept
            def dstep(carry, i):
                dc, tok = carry
                pos = jnp.broadcast_to((t0 + i)[None, None], (b, 1))
                lg, mut = draft.apply({"params": dparams, "cache": dc},
                                      tok[:, None], pos,
                                      mutable=["cache"])
                nxt = jnp.argmax(_full_vocab(lg[:, -1]), -1).astype(
                    jnp.int32)
                return (mut["cache"], nxt), nxt

            (dcache, _), ds = jax.lax.scan(dstep, (dcache, last),
                                           jnp.arange(k + 1))
            d = ds[:k].T  # [b, k]; ds[k] is the completion feed's output

            # target verifies the whole window in one chunk: v[:, i]
            # predicts the position after chunk[:, i] (the shared
            # verification body — the serving engine runs the same one)
            chunk = jnp.concatenate([last[:, None], d], axis=1)
            cpos = jnp.broadcast_to((t0 + jnp.arange(k + 1))[None, :],
                                    (b, k + 1))
            tcache, v, _ = verify_step(target, tparams, tcache, chunk,
                                       cpos)

            match = (d == v[:, :k]).astype(jnp.int32)
            a = jnp.min(jnp.sum(jnp.cumprod(match, axis=1), axis=1))
            corr = jax.lax.dynamic_index_in_dim(v, a, axis=1,
                                                keepdims=False)
            base = jnp.concatenate([d, d[:, -1:]], axis=1)
            emit = jnp.where(jnp.arange(k + 1)[None, :] == a,
                             corr[:, None], base)
            out = jax.lax.dynamic_update_slice(out, emit, (0, n))
            n = n + a + 1
            # both caches must hold exactly the positions before the new
            # `last` (at plen + n - 1); stale tail entries are masked
            t_new = plen + n - 1
            return (n, corr, out, _set_cache_index(tcache, t_new),
                    _set_cache_index(dcache, t_new))

        n, _, out, _, _ = jax.lax.while_loop(
            cond, body, (n0, last, out, tcache, dcache))
        out = out[:, :max_new]
        if eos_token_id is not None:
            is_eos = (out == eos_token_id).astype(jnp.int32)
            after = (jnp.cumsum(is_eos, axis=1) - is_eos) > 0
            out = jnp.where(after, pad_token_id, out)
        return out

    return run


def speculative_generate(target_model, target_params, draft_model,
                         draft_params, prompt_tokens,
                         max_new_tokens: int, *, num_draft_tokens: int = 4,
                         eos_token_id: Optional[int] = None,
                         pad_token_id: int = 0):
    """Greedy speculative decoding: a small draft model proposes
    ``num_draft_tokens`` per round, the target verifies them in one
    chunked forward over its KV cache, and the accepted prefix plus one
    target token is emitted. Token-exact vs ``generate(target, ...)``
    greedy — every output token is a target-argmax over the same prefix
    (the draft only affects HOW MANY target forwards are needed, never
    the result). Sampling is not supported (rejection-sampling
    speculative decoding is a different contract); both models must be
    built with ``decode=True`` and share a tokenizer/vocab.

    The cache-rollback trick: decode attention masks by absolute
    position against each layer's scalar ``cache_index``, so rejecting
    draft tokens costs one index reset — stale K/V rows stay resident
    but invisible until overwritten."""
    from apex_tpu.transformer.parallel_state import (
        get_tensor_model_parallel_world_size,
    )

    if get_tensor_model_parallel_world_size() > 1:
        raise NotImplementedError(
            "speculative_generate() drives tp=1 models")
    if num_draft_tokens < 1:
        raise ValueError(f"num_draft_tokens ({num_draft_tokens}) must "
                         f"be >= 1")
    if (target_model.config.vocab_size
            != draft_model.config.vocab_size):
        raise ValueError(
            f"target vocab ({target_model.config.vocab_size}) != draft "
            f"vocab ({draft_model.config.vocab_size}): draft proposals "
            f"would be clamped/garbled in the target embedding — the "
            f"models must share a tokenizer")
    for m in (target_model, draft_model):
        # the draft window overshoots by up to num_draft_tokens beyond
        # the emitted tokens, so validate with that headroom included
        _validate_decode("speculative_generate", m, prompt_tokens,
                         max_new_tokens, extra=num_draft_tokens)
    b, plen = prompt_tokens.shape
    run = _compiled_speculative(
        target_model, draft_model, plen, max_new_tokens,
        int(num_draft_tokens), eos_token_id, pad_token_id)
    tcache = init_cache(target_model, b, prompt_tokens.dtype)
    dcache = init_cache(draft_model, b, prompt_tokens.dtype)
    out = run(target_params, draft_params, tcache, dcache, prompt_tokens)
    return jnp.concatenate([prompt_tokens, out], axis=1)


def init_params_tp(model, key, sample_tokens, mesh=None):
    """Initialize a decode/serving model's params under the 'tp' axis.

    Returns a *stacked* pytree (leading [tp] dim per leaf, leaf i = rank
    i's local shard — the same convention as the pipelined harness) for
    :func:`tensor_parallel_generate`. Init keys are rank-folded inside
    the TP layers, so the sharded model is self-consistent.
    """
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    mesh = mesh or parallel_state.get_mesh()

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(), P()),
                       out_specs=P("tp"), check_vma=False)
    def init_fn(k, tok):
        variables = model.init(k, tok)
        return jax.tree_util.tree_map(lambda a: a[None],
                                      variables["params"])

    return init_fn(key, sample_tokens)


def tensor_parallel_generate(model, stacked_params, prompt_tokens,
                             max_new_tokens: int, *, mesh=None, rng=None,
                             temperature: float = 1.0,
                             top_k: Optional[int] = None,
                             top_p: Optional[float] = None,
                             eos_token_id: Optional[int] = None,
                             pad_token_id: int = 0):
    """Tensor-parallel KV-cache decoding: the whole prefill + scan loop
    runs inside ONE shard_map over the 'tp' mesh axis (vocab-parallel
    logits are gathered per step by the compiled decode step, so
    sampling sees the full vocabulary and — with the shared rng — every
    rank picks identical tokens). ``stacked_params`` is the leading-[tp]
    layout from :func:`init_params_tp`. Multi-chip serving path; the
    reference has no serving story at all.
    """
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    mesh = mesh or parallel_state.get_mesh()
    prefill, decode_all, rng = _prep_decode(
        "tensor_parallel_generate", model, prompt_tokens, max_new_tokens,
        rng, temperature, top_k, top_p, eos_token_id, pad_token_id)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("tp"), P(), P()), out_specs=P(),
                       check_vma=False)
    def run(sp, toks, key):
        params = jax.tree_util.tree_map(lambda a: a[0], sp)
        return _prefill_and_decode(prefill, decode_all, model, params,
                                   toks, key)

    out = run(stacked_params, prompt_tokens, rng)
    return jnp.concatenate([prompt_tokens, out], axis=1)
