"""Beam search over encoder-decoder KV-cache decode (T5, Whisper).

One generic static-shaped beam engine driven by model-specific prefill/
step closures. The algorithm reproduces HuggingFace generate semantics
(BeamSearchScorer, early_stopping=False):

- 2k candidates per step from the k running beams, so the running set
  refills to k even when candidates hit EOS;
- a candidate ending in EOS leaves the running set and enters a size-k
  finished-hypothesis pool, scored sum_logprobs / generated_len **
  length_penalty with generated_len counting the EOS (HF cur_len + 1
  convention, decoder prompt excluded);
- a batch row is done once its worst finished score can no longer be
  beaten by the best running sum at the current length; its state then
  freezes (HF stops collecting hypotheses at exactly this point);
- at the end, still-running beams of not-done rows are finalized at
  generated_len = max_new_tokens and compete with the pool.

Everything is lax-friendly: the loop is a scan over max_new_tokens, the
pools are fixed [b, k] tensors, and per-beam caches are reordered by a
batched gather on the cache's batch axis (axis 1 — the [s, b, n, d]
layout both families' attention caches use; scalar position counters
pass through untouched). No reference counterpart (apex is
training-only); the oracle is HF generate(num_beams=k) token output.
"""

import jax
import jax.numpy as jnp

NEG_INF = -1.0e9


def tile_cache_for_beams(cache, num_beams):
    """[.., b, ..] -> [.., b*k, ..] along the cache batch axis (axis 1);
    scalars (position counters) pass through."""
    return jax.tree_util.tree_map(
        lambda x: x if x.ndim == 0 else jnp.repeat(x, num_beams, axis=1),
        cache)


def _reorder_cache(cache, gather):
    return jax.tree_util.tree_map(
        lambda x: x if x.ndim == 0 else jnp.take(x, gather, axis=1),
        cache)


def beam_search_cached(step_fn, cache, first_logits, *, num_beams,
                       max_new_tokens, eos_token_id, pad_token_id=0,
                       length_penalty=1.0):
    """Generic cached-decode beam search.

    step_fn(cache, tok [b*k] int32) -> (full-vocab logits [b*k, v],
    new cache): one single-token decoder step. ``cache`` must already be
    tiled to b*k rows (``tile_cache_for_beams``) and prefetched with the
    decoder start token; ``first_logits`` [b, v] are the start token's
    full-vocab logits from that prefill.

    Returns (tokens [b, max_new_tokens] — EOS then pad on finished rows,
    HF layout — and [b] final scores).
    """
    k = num_beams
    b, vocab = first_logits.shape
    N = max_new_tokens
    no_eos = eos_token_id is None
    eos = 0 if no_eos else eos_token_id

    def update(i, logits_bkv, state):
        (cache, run_scores, run_seqs, fin_scores, fin_seqs, done) = state
        lp = jax.nn.log_softmax(logits_bkv.astype(jnp.float32))
        total = lp + run_scores[:, :, None]
        cand_scores, cand_flat = jax.lax.top_k(
            total.reshape(b, k * vocab), 2 * k)
        cand_beam = cand_flat // vocab                      # [b, 2k]
        cand_tok = cand_flat % vocab
        cand_seqs = jnp.take_along_axis(run_seqs, cand_beam[:, :, None],
                                        axis=1)             # [b, 2k, N]
        cand_seqs = cand_seqs.at[:, :, i].set(cand_tok)
        finished_now = (jnp.zeros_like(cand_tok, bool) if no_eos
                        else cand_tok == eos)

        # running set: EOS candidates drop out, best k survivors refill
        live = jnp.where(finished_now, NEG_INF, cand_scores)
        new_run_scores, sel = jax.lax.top_k(live, k)
        new_tok = jnp.take_along_axis(cand_tok, sel, axis=1)
        new_run_seqs = jnp.take_along_axis(cand_seqs, sel[:, :, None],
                                           axis=1)
        src_beam = jnp.take_along_axis(cand_beam, sel, axis=1)

        # finished pool: HF normalizes by the generated length INCLUDING
        # the EOS (cur_len + 1 - decoder_prompt_len = i + 1); i may be a
        # scan tracer, so the power stays in jnp
        gen_len = (jnp.asarray(i, jnp.float32) + 1.0) ** length_penalty
        norm = cand_scores / gen_len
        norm = jnp.where(finished_now, norm, NEG_INF)
        pool_scores = jnp.concatenate([fin_scores, norm], axis=1)
        pool_seqs = jnp.concatenate([fin_seqs, cand_seqs], axis=1)
        new_fin_scores, fsel = jax.lax.top_k(pool_scores, k)
        new_fin_seqs = jnp.take_along_axis(pool_seqs, fsel[:, :, None],
                                           axis=1)

        # HF is_done (early_stopping=False): k hypotheses exist AND the
        # best running sum can no longer beat the worst of them at the
        # current generated length
        worst_fin = new_fin_scores[:, -1]   # NEG_INF while pool not full
        best_possible = new_run_scores[:, 0] / gen_len
        now_done = done | (worst_fin >= best_possible)

        # freeze rows that were already done BEFORE this step (HF stops
        # adding hypotheses the moment is_done fires)
        frz = done[:, None]
        new_run_scores = jnp.where(frz, run_scores, new_run_scores)
        new_run_seqs = jnp.where(frz[:, :, None], run_seqs, new_run_seqs)
        new_fin_scores = jnp.where(frz, fin_scores, new_fin_scores)
        new_fin_seqs = jnp.where(frz[:, :, None], fin_seqs, new_fin_seqs)
        now_done = jnp.where(done, done, now_done)
        new_tok = jnp.where(frz, pad_token_id, new_tok)
        src_beam = jnp.where(frz, jnp.arange(k)[None, :], src_beam)

        gather = (jnp.arange(b)[:, None] * k + src_beam).reshape(b * k)
        cache = _reorder_cache(cache, gather)
        state = (cache, new_run_scores, new_run_seqs, new_fin_scores,
                 new_fin_seqs, now_done)
        return state, new_tok.reshape(b * k)

    # step 0: every tiled beam is identical, so score only beam 0 and
    # let the generic update spread the top-k picks across beams
    run_scores0 = jnp.full((b, k), NEG_INF).at[:, 0].set(0.0)
    state = (cache, run_scores0,
             jnp.full((b, k, N), pad_token_id, jnp.int32),
             jnp.full((b, k), NEG_INF),
             jnp.full((b, k, N), pad_token_id, jnp.int32),
             jnp.zeros((b,), bool))
    logits0 = jnp.broadcast_to(first_logits[:, None, :], (b, k, vocab))
    state, tok = update(0, logits0, state)

    def scan_step(carry, i):
        state, tok = carry
        logits, new_cache = step_fn(state[0], tok)
        state = (new_cache,) + state[1:]
        state, tok = update(i, logits.reshape(b, k, vocab), state)
        return (state, tok), None

    if N > 1:
        (state, _), _ = jax.lax.scan(scan_step, (state, tok),
                                     jnp.arange(1, N))
    (_, run_scores, run_seqs, fin_scores, fin_seqs, done) = state

    # finalize: not-done rows contribute their running beams at
    # generated_len = N (HF finalize semantics)
    final_norm = run_scores / float(N ** length_penalty)
    final_norm = jnp.where(done[:, None], NEG_INF, final_norm)
    pool_scores = jnp.concatenate([fin_scores, final_norm], axis=1)
    pool_seqs = jnp.concatenate([fin_seqs, run_seqs], axis=1)
    best = jnp.argmax(pool_scores, axis=1)                   # [b]
    best_seqs = jnp.take_along_axis(
        pool_seqs, best[:, None, None], axis=1)[:, 0]        # [b, N]
    best_scores = jnp.take_along_axis(pool_scores, best[:, None],
                                      axis=1)[:, 0]
    return best_seqs, best_scores
