"""Reshard a full single-program GPT checkpoint into the 3D-parallel
per-stage layout.

Closes the loop from single-device checkpoints (the tools/ HF converters,
``apex_tpu.checkpoint`` saves) to pipelined training: the reference keeps
per-rank checkpoint files and loads each rank's file into its own process
(its ``parallel_state`` embedding groups assume the layout already
matches), so it has no layout-conversion tool at all. Here a checkpoint
is one pytree and the conversion is explicit:

- ``split_gpt_params_for_pp``: full ``GPTModel`` tree -> one ``GPTStage``
  tree per global stage (layer slices; embeddings/final-norm/head carried
  on every stage — ``GPTStage`` owns all of them and uses the embed on
  the first stage, the head on the last).
- ``load_checkpoint_for_3d``: the whole journey to device: pp (+vpp)
  stage split, per-stage TP shard split (``tp_split`` rules), then
  placement into the exact per-rank stacked layout
  ``testing.gpt_3d.build_gpt_3d_harness`` trains on (leading [pp] mesh
  axis, per-rank [vpp] chunk axis, TP shards per (pp, tp) cell).

Tied-embedding checkpoints (``cfg.tie_word_embeddings``) are untied on
the way in: pipeline stages cannot share the embedding table across
ranks (same constraint as the reference's parallel_lm_logits), so the
head weight is materialized as ``embedding.T``.

Memory note: placement temporarily replicates the stacked
[stages, tp, ...] host tree to every device before each rank picks its
cell — sized for single-host loading (the intended use: HF-converted or
locally saved checkpoints). Oracle tests: tests/L0/test_reshard_3d.py.
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.models.tp_split import (
    _dense_tp_rule,
    _path_names,
    _replicate,
    _split_contiguous,
    _split_two_region,
    split_params_for_tp,
)


def split_gpt_params_for_pp(cfg, params, pp, vpp=1):
    """Full GPTModel param tree -> list of ``pp * vpp`` GPTStage trees,
    ordered by global stage (chunk-major: stage s holds layers
    ``s*lps .. (s+1)*lps-1``)."""
    S = pp * (vpp or 1)
    L = cfg.num_layers
    if L % S:
        raise ValueError(
            f"num_layers ({L}) must be a multiple of pp*vpp ({S})")
    lps = L // S

    tree = dict(params)
    trans = dict(tree.pop("transformer"))
    shared = tree
    if "lm_head" not in shared:
        # tied checkpoint: stages need an untied head (module docstring)
        shared = dict(shared)
        shared["lm_head"] = jnp.transpose(
            shared["word_embeddings"]["weight"])

    scan = "layers" in trans  # scan_layers stack: leaves lead with [L]
    stages = []
    for s in range(S):
        if scan:
            sub = {"layers": jax.tree_util.tree_map(
                lambda a, s=s: a[s * lps:(s + 1) * lps], trans["layers"])}
        else:
            missing = [f"layer_{s * lps + i}" for i in range(lps)
                       if f"layer_{s * lps + i}" not in trans]
            if missing:
                raise ValueError(
                    f"checkpoint transformer tree lacks {missing}; keys "
                    f"present: {sorted(trans)}")
            sub = {f"layer_{i}": trans[f"layer_{s * lps + i}"]
                   for i in range(lps)}
        stages.append({**shared, "transformer": sub})
    return stages


def _axis_index_or_zero(mesh, name):
    return jax.lax.axis_index(name) if name in mesh.shape else 0


def load_checkpoint_for_3d(cfg, params, mesh, *, pp, vpp=1):
    """Full GPTModel params -> the stacked per-rank pytree that
    ``build_gpt_3d_harness``'s step consumes (same device layout its own
    ``init_params`` produces: P('pp')-stacked, TP shards resident per
    (pp, tp) cell, [vpp] chunk axis per rank when vpp > 1)."""
    from jax.sharding import PartitionSpec as P

    V = vpp or 1
    tp = mesh.shape.get("tp", 1)
    stages = split_gpt_params_for_pp(cfg, params, pp, V)
    # host-side: [stages, tp, ...] per leaf
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[split_params_for_tp(cfg, st, tp) for st in stages])

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(),),
                       out_specs=P("pp"), check_vma=False)
    def place(all_stages):
        r = _axis_index_or_zero(mesh, "pp")
        t = _axis_index_or_zero(mesh, "tp")

        def pick(leaf, s):
            x = jax.lax.dynamic_index_in_dim(leaf, s, 0, keepdims=False)
            return jax.lax.dynamic_index_in_dim(x, t, 0, keepdims=False)

        if V > 1:
            # chunk c on rank r is global stage c*pp + r (gpt_3d layout)
            chunks = [jax.tree_util.tree_map(
                lambda a, c=c: pick(a, c * pp + r), all_stages)
                for c in range(V)]
            local = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunks)
        else:
            local = jax.tree_util.tree_map(lambda a: pick(a, r),
                                           all_stages)
        return jax.tree_util.tree_map(lambda a: a[None], local)

    return jax.jit(place)(stacked)


def split_moe_params_for_ep(cfg, params, ep, tp=1):
    """Full single-program MoE GPT tree (e.g. tools/convert_hf_mixtral
    output) -> leaves stacked [ep, tp, ...]:

    - expert leaves (``mlp/experts/*``, leading global-expert axis [E]):
      E sliced across ep ranks; the tp split follows the ExpertMLP
      layout — w1 columns (two-region [gate | up] when the expert
      activation is gated), w2 input rows, b1 columns; b2 replicates
      (added once after the tp psum).
    - router weights: replicated (dense math, every rank routes).
    - everything else: the dense-GPT tp rules, replicated over ep.
    """
    E = cfg.num_moe_experts
    if not E:
        raise ValueError("cfg has no MoE experts; use split_params_for_tp")
    if E % ep:
        raise ValueError(f"num_moe_experts ({E}) not divisible by ep ({ep})")
    gated = cfg.activation in ("swiglu", "geglu")
    dense = _dense_tp_rule(cfg, tp) if tp > 1 else (
        lambda path, leaf: leaf[None])

    def expert_tp_split(name, x):
        if tp == 1:
            return x[None]
        if name == "w1":
            if gated:
                return _split_two_region(x, tp, cfg.ffn_size, -1)
            return _split_contiguous(x, tp, -1)
        if name == "w2":
            return _split_contiguous(x, tp, -2)
        if name == "b1":
            return _split_contiguous(x, tp, -1)
        if name == "b2":
            return _replicate(x, tp)
        raise ValueError(f"unknown expert param {name!r}")

    def rule(path, leaf):
        names = _path_names(path)
        if "experts" in names:
            # scan_layers trees stack all layers under 'layers', so the
            # global-expert axis sits behind the leading [num_layers]
            e_axis = 1 if "layers" in names else 0
            shards = jnp.split(leaf, ep, axis=e_axis)  # slice the E axis
            return jnp.stack([expert_tp_split(names[-1], x)
                              for x in shards])  # [ep, tp, ...]
        if "router" in names:
            return _replicate(_replicate(leaf, tp), ep)
        out = dense(path, leaf)  # [tp, ...]
        return _replicate(out, ep)

    return jax.tree_util.tree_map_with_path(rule, params)


def load_moe_checkpoint_for_ep(cfg, params, mesh):
    """Full single-program MoE GPT params -> the stacked per-rank pytree
    ``testing.gpt_moe.build_gpt_moe_harness`` consumes (same device
    layout its own ``init_params`` produces over the ('ep', 'tp') mesh
    axes)."""
    from jax.sharding import PartitionSpec as P

    ep = mesh.shape.get("ep", 1)
    tp = mesh.shape.get("tp", 1)
    stacked = split_moe_params_for_ep(cfg, params, ep, tp)
    model_axes = tuple(a for a in ("ep", "tp") if a in mesh.shape)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(),),
                       out_specs=P(model_axes), check_vma=False)
    def place(all_ranks):
        e = _axis_index_or_zero(mesh, "ep")
        t = _axis_index_or_zero(mesh, "tp")

        def pick(leaf):
            x = jax.lax.dynamic_index_in_dim(leaf, e, 0, keepdims=False)
            return jax.lax.dynamic_index_in_dim(x, t, 0, keepdims=False)

        local = jax.tree_util.tree_map(pick, all_ranks)
        return jax.tree_util.tree_map(lambda a: a[None], local)

    return jax.jit(place)(stacked)
