"""BERT model on the parallel transformer stack.

Parity: reference apex/transformer/testing/standalone_bert.py (255 LoC):
bidirectional (padding-mask) transformer with token-type embeddings, MLM
head (dense + gelu + LN + tied-vocab projection) and binary NSP head.
"""


import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.models.gpt import _fold_tp
from apex_tpu.models.transformer_lm import (
    ParallelTransformer,
    TransformerConfig,
)
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


class BertModel(nn.Module):
    """Returns (mlm_logits [b, s, vocab/tp], nsp_logits [b, 2])."""

    config: TransformerConfig
    num_tokentypes: int = 2
    add_binary_head: bool = True

    @nn.compact
    def __call__(self, tokens, padding_mask=None, tokentype_ids=None,
                 position_ids=None):
        cfg = self.config
        assert cfg.attn_mask_type == AttnMaskType.padding, (
            "BERT is bidirectional: config.attn_mask_type must be "
            "AttnMaskType.padding (got causal; the transformer stack would "
            "silently apply a causal mask)")
        emb = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            params_dtype=cfg.params_dtype, name="word_embeddings")
        h = emb(tokens)
        if position_ids is None:
            position_ids = jnp.arange(tokens.shape[-1])[None, :]
        pos = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings, cfg.hidden_size),
                         cfg.params_dtype)
        h = h + pos[position_ids]
        if tokentype_ids is not None:
            tt = self.param("tokentype_embeddings",
                            nn.initializers.normal(0.02),
                            (self.num_tokentypes, cfg.hidden_size),
                            cfg.params_dtype)
            h = h + tt[tokentype_ids]
        h = h.astype(cfg.compute_dtype).transpose(1, 0, 2)  # [s, b, h]

        # padding mask: [b, s] 1=keep -> attention mask [b, 1, s, s]
        attention_mask = None
        if padding_mask is not None:
            keep = padding_mask.astype(bool)
            attention_mask = ~(keep[:, None, None, :] & keep[:, None, :, None])

        h = ParallelTransformer(cfg, name="transformer")(h, attention_mask)
        h = FusedLayerNorm(normalized_shape=cfg.hidden_size,
                           eps=cfg.layernorm_epsilon, param_dtype=jnp.float32,
                           name="final_layernorm")(h.astype(jnp.float32))

        # MLM head (reference BertLMHead): dense+gelu+LN then vocab proj
        x = nn.Dense(cfg.hidden_size, param_dtype=cfg.params_dtype,
                     name="lm_dense")(h.astype(cfg.compute_dtype))
        x = jnp.asarray(nn.gelu(x.astype(jnp.float32)), cfg.compute_dtype)
        x = FusedLayerNorm(normalized_shape=cfg.hidden_size,
                           eps=cfg.layernorm_epsilon, param_dtype=jnp.float32,
                           name="lm_layernorm")(x.astype(jnp.float32))
        tp = get_tensor_model_parallel_world_size()
        vocab_per_rank = divide(cfg.vocab_size, tp)
        head = self.param(
            "lm_head",
            lambda key, shape, dtype: nn.initializers.normal(0.02)(
                _fold_tp(key), shape, dtype),
            (cfg.hidden_size, vocab_per_rank), cfg.params_dtype)
        x = copy_to_tensor_model_parallel_region(x.astype(cfg.compute_dtype))
        mlm_logits = jnp.einsum("sbh,hv->sbv", x,
                                head.astype(cfg.compute_dtype),
                                preferred_element_type=jnp.float32)
        mlm_logits = mlm_logits.transpose(1, 0, 2)

        nsp_logits = None
        if self.add_binary_head:
            # pooled [CLS] (first token) -> tanh dense -> binary head
            pooled = nn.Dense(cfg.hidden_size, param_dtype=cfg.params_dtype,
                              name="pooler")(h[0].astype(cfg.compute_dtype))
            pooled = jnp.tanh(pooled.astype(jnp.float32))
            nsp_logits = nn.Dense(2, param_dtype=cfg.params_dtype,
                                  name="binary_head")(
                pooled.astype(cfg.compute_dtype)).astype(jnp.float32)
        return mlm_logits, nsp_logits


def bert_loss_fn(mlm_logits, nsp_logits, labels, loss_mask,
                 nsp_labels=None):
    """MLM CE (vocab-parallel) + optional NSP CE
    (reference standalone_bert loss)."""
    mlm_losses = vocab_parallel_cross_entropy(mlm_logits, labels)
    lm_loss = jnp.sum(mlm_losses * loss_mask) / jnp.maximum(
        jnp.sum(loss_mask), 1.0)
    if nsp_logits is not None and nsp_labels is not None:
        nsp_logp = nsp_logits - jnp.log(
            jnp.sum(jnp.exp(nsp_logits), axis=-1, keepdims=True))
        nsp_loss = -jnp.mean(
            jnp.take_along_axis(nsp_logp, nsp_labels[:, None], axis=-1))
        return lm_loss + nsp_loss
    return lm_loss
