"""Multi-head Latent Attention (DeepSeek-V2) language model.

The KV-cache-compression attention innovation for the zoo: K/V are
projected through a small shared LATENT (``kv_lora_rank`` wide, plus a
decoupled rope sub-vector shared across heads) and re-expanded per head,
shrinking the cache by an order of magnitude — directly relevant on TPU
where HBM capacity bounds batch at decode. Queries optionally compress
through their own latent (``q_lora_rank``; deepseek-v2-lite skips it).

Layout (DeepSeek-V2 conventions, validated against HF by the converter
oracle): per head, queries/keys carry ``qk_nope_head_dim`` positionless
channels plus ``qk_rope_head_dim`` rotary channels (the key's rope
sub-vector comes from the latent projection and is SHARED by all heads);
values carry ``v_head_dim``. Scores scale by (nope+rope)**-0.5. The
rotary uses the interleaved-pair convention (HF's internal de-interleave
permute cancels in the q·k contraction). RMSNorm everywhere, SwiGLU MLP,
untied head.

TP design: the latent projections (q_a, kv_a) are small and REPLICATED;
the per-head expansions (q_b, kv_b) are column-parallel over heads and
the output projection is row-parallel — so the latent rides every rank
while heads shard, the same geometry the cache savings want.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.transformer_lm import _rope_core
from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class MLAConfig:
    vocab_size: int = 102400
    hidden_size: int = 2048
    num_layers: int = 12
    num_heads: int = 16
    q_lora_rank: Optional[int] = None   # None -> direct q projection
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    ffn_hidden_size: int = 8192
    rms_eps: float = 1e-6
    rotary_base: float = 10000.0
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16

    @property
    def qk_head_dim(self):
        return self.qk_nope_head_dim + self.qk_rope_head_dim


def _norm(cfg, name, width=None):
    return FusedRMSNorm(normalized_shape=width or cfg.hidden_size,
                        eps=cfg.rms_eps, param_dtype=jnp.float32,
                        name=name)


class MLAAttention(nn.Module):
    """Latent-compressed attention (module doc)."""

    config: MLAConfig

    @nn.compact
    def __call__(self, x, position_ids=None):
        cfg = self.config
        tp = get_tensor_model_parallel_world_size()
        n_local = divide(cfg.num_heads, tp)
        nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
        vd = cfg.v_head_dim
        s, b, _ = x.shape
        x = x.astype(cfg.compute_dtype)

        # -- queries: optional latent compression, then per-head expand
        if cfg.q_lora_rank:
            qa = nn.Dense(cfg.q_lora_rank, use_bias=False,
                          dtype=cfg.compute_dtype,
                          param_dtype=cfg.params_dtype, name="q_a")(x)
            qa = _norm(cfg, "q_a_norm", cfg.q_lora_rank)(
                qa.astype(jnp.float32)).astype(cfg.compute_dtype)
            qa = copy_to_tensor_model_parallel_region(qa)
            q = ColumnParallelLinear(
                input_size=cfg.q_lora_rank,
                output_size=cfg.num_heads * cfg.qk_head_dim,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="q_b")(qa)
        else:
            q = ColumnParallelLinear(
                input_size=cfg.hidden_size,
                output_size=cfg.num_heads * cfg.qk_head_dim,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="q_b")(x)
        q = q.reshape(s, b, n_local, cfg.qk_head_dim)
        q_nope, q_pe = q[..., :nope], q[..., nope:]

        # -- keys/values: shared latent + shared rope sub-vector
        ckv = nn.Dense(cfg.kv_lora_rank + rope, use_bias=False,
                       dtype=cfg.compute_dtype,
                       param_dtype=cfg.params_dtype, name="kv_a")(x)
        compressed, k_pe = ckv[..., :cfg.kv_lora_rank], \
            ckv[..., cfg.kv_lora_rank:]
        compressed = _norm(cfg, "kv_a_norm", cfg.kv_lora_rank)(
            compressed.astype(jnp.float32)).astype(cfg.compute_dtype)
        compressed = copy_to_tensor_model_parallel_region(compressed)
        kv = ColumnParallelLinear(
            input_size=cfg.kv_lora_rank,
            output_size=cfg.num_heads * (nope + vd),
            gather_output=False, bias=False,
            params_dtype=cfg.params_dtype, name="kv_b")(compressed)
        kv = kv.reshape(s, b, n_local, nope + vd)
        k_nope, value = kv[..., :nope], kv[..., nope:]

        # rope on the decoupled sub-vectors (interleaved convention; the
        # key rope part is one shared "head" broadcast after rotation)
        q_pe = _rope_core(q_pe, cfg.rotary_base, position_ids, rope,
                          interleaved=True)
        k_pe = _rope_core(k_pe[:, :, None, :], cfg.rotary_base,
                          position_ids, rope, interleaved=True)
        k_pe = jnp.broadcast_to(k_pe, (s, b, n_local, rope))

        scale = jnp.asarray(cfg.qk_head_dim ** -0.5, jnp.float32)
        scores = (jnp.einsum("qbnd,kbnd->bnqk",
                             jnp.concatenate([q_nope, q_pe], -1).astype(
                                 cfg.compute_dtype),
                             jnp.concatenate([k_nope, k_pe], -1).astype(
                                 cfg.compute_dtype),
                             preferred_element_type=jnp.float32) * scale)
        i = jnp.arange(s)[:, None]
        j = jnp.arange(s)[None, :]
        scores = jnp.where(j > i, -1e9, scores)  # causal
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bnqk,kbnd->qbnd",
                         probs.astype(cfg.compute_dtype),
                         value.astype(cfg.compute_dtype),
                         preferred_element_type=jnp.float32)
        ctx = ctx.reshape(s, b, n_local * vd).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=cfg.num_heads * vd, output_size=cfg.hidden_size,
            input_is_parallel=True, bias=False,
            params_dtype=cfg.params_dtype, name="o")(ctx)


class _SwiGLU(nn.Module):
    config: MLAConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = x.astype(cfg.compute_dtype)
        gate_up = ColumnParallelLinear(
            input_size=cfg.hidden_size, output_size=2 * cfg.ffn_hidden_size,
            gather_output=False, bias=False,
            params_dtype=cfg.params_dtype, name="gate_up")(x)
        gate, up = jnp.split(gate_up.astype(jnp.float32), 2, axis=-1)
        h = (jax.nn.silu(gate) * up).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=cfg.ffn_hidden_size, output_size=cfg.hidden_size,
            input_is_parallel=True, bias=False,
            params_dtype=cfg.params_dtype, name="down")(h)


class DeepseekBlock(nn.Module):
    config: MLAConfig

    @nn.compact
    def __call__(self, h, position_ids=None):
        cfg = self.config
        x = _norm(cfg, "input_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        h = h + MLAAttention(cfg, name="self_attn")(
            x, position_ids).astype(h.dtype)
        x = _norm(cfg, "post_attn_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        return h + _SwiGLU(cfg, name="mlp")(x).astype(h.dtype)


class DeepseekModel(nn.Module):
    """Dense DeepSeek-V2-style causal LM on MLA. Token ids [b, s] ->
    [b, s, vocab/tp] logits. (The MoE layers of the large DeepSeek
    checkpoints route through ``transformer/moe``'s SwitchMLP — this
    family pins the attention innovation with the dense configuration.)
    """

    config: MLAConfig

    @nn.compact
    def __call__(self, tokens, position_ids=None):
        cfg = self.config
        h = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            params_dtype=cfg.params_dtype, name="embed_tokens")(tokens)
        h = h.astype(cfg.compute_dtype).transpose(1, 0, 2)  # [s, b, h]
        pos = (position_ids.transpose(1, 0)
               if position_ids is not None else None)
        for i in range(cfg.num_layers):
            h = DeepseekBlock(cfg, name=f"layer_{i}")(h, pos)
        h = _norm(cfg, "final_norm")(h.astype(jnp.float32))
        h = copy_to_tensor_model_parallel_region(
            h.astype(cfg.compute_dtype))
        tp = get_tensor_model_parallel_world_size()
        head = self.param("lm_head", nn.initializers.normal(0.02),
                          (cfg.hidden_size, divide(cfg.vocab_size, tp)),
                          cfg.params_dtype)
        logits = jnp.einsum("sbh,hv->sbv", h,
                            head.astype(cfg.compute_dtype),
                            preferred_element_type=jnp.float32)
        return logits.transpose(1, 0, 2)


def mla_greedy_generate(model, params, prompt_tokens, max_new_tokens):
    """Greedy decode (full re-run per token — oracle path)."""
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    toks = jnp.asarray(prompt_tokens, jnp.int32)
    for _ in range(max_new_tokens):
        logits = model.apply({"params": params}, toks)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        nxt = jnp.argmax(full, -1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks
