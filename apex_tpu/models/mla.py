"""Multi-head Latent Attention (DeepSeek-V2) language model.

The KV-cache-compression attention innovation for the zoo: K/V are
projected through a small shared LATENT (``kv_lora_rank`` wide, plus a
decoupled rope sub-vector shared across heads) and re-expanded per head,
shrinking the cache by an order of magnitude — directly relevant on TPU
where HBM capacity bounds batch at decode. Queries optionally compress
through their own latent (``q_lora_rank``; deepseek-v2-lite skips it).

Layout (DeepSeek-V2 conventions, validated against HF by the converter
oracle): per head, queries/keys carry ``qk_nope_head_dim`` positionless
channels plus ``qk_rope_head_dim`` rotary channels (the key's rope
sub-vector comes from the latent projection and is SHARED by all heads);
values carry ``v_head_dim``. Scores scale by (nope+rope)**-0.5. The
rotary uses the interleaved-pair convention (HF's internal de-interleave
permute cancels in the q·k contraction). RMSNorm everywhere, SwiGLU MLP,
untied head.

TP design: the latent projections (q_a, kv_a) are small and REPLICATED;
the per-head expansions (q_b, kv_b) are column-parallel over heads and
the output projection is row-parallel — so the latent rides every rank
while heads shard, the same geometry the cache savings want.
"""

import dataclasses
import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.transformer_lm import _rope_core
from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class MLAConfig:
    vocab_size: int = 102400
    hidden_size: int = 2048
    num_layers: int = 12
    num_heads: int = 16
    q_lora_rank: Optional[int] = None   # None -> direct q projection
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    ffn_hidden_size: int = 8192
    rms_eps: float = 1e-6
    rotary_base: float = 10000.0
    max_decode_length: int = 512   # latent-cache window for decoding
    # DeepSeek MoE layers (None -> dense everywhere). Layers >=
    # first_k_dense_replace route top-k over n_routed_experts small
    # experts (greedy gate, raw softmax mass unless norm_topk_prob,
    # output scaled by routed_scaling_factor) PLUS an always-on shared
    # expert of n_shared_experts * moe_intermediate_size width.
    n_routed_experts: Optional[int] = None
    moe_intermediate_size: Optional[int] = None
    n_shared_experts: Optional[int] = None
    moe_top_k: int = 2
    routed_scaling_factor: float = 1.0
    norm_topk_prob: bool = False
    first_k_dense_replace: int = 0
    # None -> dropless (E/k, the HF-parity semantics: every token reaches
    # its routed experts). Training users can cap it (e.g. 1.25) without
    # forking the block; dropped tokens then ride the residual.
    moe_capacity_factor: Optional[float] = None
    # auto -> ragged grouped-matmul when dropless on one ep rank,
    # scatter otherwise (see transformer/moe/layer.py SwitchMLP).
    moe_dispatch_mode: str = "auto"
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16

    @property
    def qk_head_dim(self):
        return self.qk_nope_head_dim + self.qk_rope_head_dim


def _norm(cfg, name, width=None):
    return FusedRMSNorm(normalized_shape=width or cfg.hidden_size,
                        eps=cfg.rms_eps, param_dtype=jnp.float32,
                        name=name)


class MLAAttention(nn.Module):
    """Latent-compressed attention (module doc). ``mode`` (static):
    'train' — full attention; 'prefill'/'step' — the ABSORBED-projection
    latent-cache decode: the cache holds ONLY the per-token latent row
    [kv_lora_rank + qk_rope_head_dim] (normed latent | rotated shared
    k_pe), shared across heads, and ``kv_b``'s halves fold into the
    attention contractions

      scores_nope[i,j] = q_nope_i . (W_nope c_j) = (W_nope^T q_nope_i) . c_j
      ctx_i            = sum_j p_ij (W_v c_j)   = W_v (sum_j p_ij c_j)

    so per-layer cache bytes shrink from 2*heads*(nope+rope) to
    (kv_rank+rope) floats/token (8-28x on the published configs) and
    per-step FLOPs over the prefix stop scaling with heads."""

    config: MLAConfig

    @nn.compact
    def __call__(self, x, position_ids=None, mode="train"):
        cfg = self.config
        tp = get_tensor_model_parallel_world_size()
        n_local = divide(cfg.num_heads, tp)
        nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
        vd, lat = cfg.v_head_dim, cfg.kv_lora_rank
        s, b, _ = x.shape
        x = x.astype(cfg.compute_dtype)

        # -- queries: optional latent compression, then per-head expand
        if cfg.q_lora_rank:
            qa = nn.Dense(cfg.q_lora_rank, use_bias=False,
                          dtype=cfg.compute_dtype,
                          param_dtype=cfg.params_dtype, name="q_a")(x)
            qa = _norm(cfg, "q_a_norm", cfg.q_lora_rank)(
                qa.astype(jnp.float32)).astype(cfg.compute_dtype)
            qa = copy_to_tensor_model_parallel_region(qa)
            q = ColumnParallelLinear(
                input_size=cfg.q_lora_rank,
                output_size=cfg.num_heads * cfg.qk_head_dim,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="q_b")(qa)
        else:
            q = ColumnParallelLinear(
                input_size=cfg.hidden_size,
                output_size=cfg.num_heads * cfg.qk_head_dim,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="q_b")(x)
        q = q.reshape(s, b, n_local, cfg.qk_head_dim)
        q_nope, q_pe = q[..., :nope], q[..., nope:]

        # -- the shared latent projection (keys/values live inside it)
        ckv = nn.Dense(lat + rope, use_bias=False,
                       dtype=cfg.compute_dtype,
                       param_dtype=cfg.params_dtype, name="kv_a")(x)

        if mode != "train":
            return self._decode_tail(cfg, x, ckv, q_nope, q_pe, n_local,
                                     nope, rope, vd, lat, s, b, mode)

        compressed, k_pe = ckv[..., :lat], ckv[..., lat:]
        compressed = _norm(cfg, "kv_a_norm", lat)(
            compressed.astype(jnp.float32)).astype(cfg.compute_dtype)
        compressed = copy_to_tensor_model_parallel_region(compressed)
        kv = ColumnParallelLinear(
            input_size=lat,
            output_size=cfg.num_heads * (nope + vd),
            gather_output=False, bias=False,
            params_dtype=cfg.params_dtype, name="kv_b")(compressed)
        kv = kv.reshape(s, b, n_local, nope + vd)
        k_nope, value = kv[..., :nope], kv[..., nope:]

        # rope on the decoupled sub-vectors (interleaved convention; the
        # key rope part is one shared "head" broadcast after rotation)
        q_pe = _rope_core(q_pe, cfg.rotary_base, position_ids, rope,
                          interleaved=True)
        k_pe = _rope_core(k_pe[:, :, None, :], cfg.rotary_base,
                          position_ids, rope, interleaved=True)
        k_pe = jnp.broadcast_to(k_pe, (s, b, n_local, rope))

        scale = jnp.asarray(cfg.qk_head_dim ** -0.5, jnp.float32)
        scores = (jnp.einsum("qbnd,kbnd->bnqk",
                             jnp.concatenate([q_nope, q_pe], -1).astype(
                                 cfg.compute_dtype),
                             jnp.concatenate([k_nope, k_pe], -1).astype(
                                 cfg.compute_dtype),
                             preferred_element_type=jnp.float32) * scale)
        i = jnp.arange(s)[:, None]
        j = jnp.arange(s)[None, :]
        scores = jnp.where(j > i, -1e9, scores)  # causal
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bnqk,kbnd->qbnd",
                         probs.astype(cfg.compute_dtype),
                         value.astype(cfg.compute_dtype),
                         preferred_element_type=jnp.float32)
        ctx = ctx.reshape(s, b, n_local * vd).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=cfg.num_heads * vd, output_size=cfg.hidden_size,
            input_is_parallel=True, bias=False,
            params_dtype=cfg.params_dtype, name="o")(ctx)

    def _decode_tail(self, cfg, x, ckv, q_nope, q_pe, n_local, nope,
                     rope, vd, lat, s, b, mode):
            compressed = _norm(cfg, "kv_a_norm", lat)(
                ckv[..., :lat].astype(jnp.float32)).astype(cfg.compute_dtype)

            # the kv_b weight READ AS A TENSOR (same param path/shape the
            # train-mode ColumnParallelLinear creates), split into its
            # absorbed halves: [lat, n*(nope+vd)] -> W_nope, W_v
            w_full = _RawWeight((lat, n_local * (nope + vd)),
                                cfg.params_dtype, name="kv_b")()
            w_full = w_full.astype(cfg.compute_dtype).reshape(
                lat, n_local, nope + vd)
            w_nope, w_v = w_full[..., :nope], w_full[..., nope:]

            pos_ctr = self.variable("cache", "pos",
                                    lambda: jnp.zeros((), jnp.int32))
            pos = jnp.zeros((), jnp.int32) if mode == "prefill" \
                else pos_ctr.value
            pos_ctr.value = pos + s
            positions = pos + jnp.arange(s)

            q_pe = _rope_core(q_pe, cfg.rotary_base, positions, rope,
                              interleaved=True)
            k_pe = _rope_core(ckv[..., None, lat:], cfg.rotary_base,
                              positions, rope, interleaved=True)[:, :, 0]

            # latent cache rows: [max_len, b, lat + rope]
            max_len = cfg.max_decode_length
            row = jnp.concatenate([compressed, k_pe], axis=-1)
            cache = self.variable("cache", "latent", jnp.zeros,
                                  (max_len, b, lat + rope), cfg.compute_dtype)
            cache.value = jax.lax.dynamic_update_slice(
                cache.value, row.astype(cfg.compute_dtype), (pos, 0, 0))
            c_lat = cache.value[..., :lat]      # [t, b, lat]
            c_pe = cache.value[..., lat:]       # [t, b, rope]

            # absorb: queries into latent space (per step, per head)
            q_lat = jnp.einsum("sbnd,lnd->sbnl", q_nope.astype(
                cfg.compute_dtype), w_nope,
                preferred_element_type=jnp.float32).astype(cfg.compute_dtype)
            scale = float(cfg.qk_head_dim ** -0.5)
            from apex_tpu.contrib import mla_decode as _mla_decode

            if (mode == "step" and s == 1
                    and _mla_decode.use_flash(max_len)):
                # Single-token hot loop: the streaming Pallas kernel —
                # cache read once for all heads, no [b, n, 1, T] score
                # round-trip through HBM, dead prefix tiles never
                # fetched (contrib/mla_decode.py). Gated on use_flash so
                # every non-kernel configuration runs the einsum path
                # below, not the kernel module's fp32 fallback.
                q_full = jnp.concatenate(
                    [q_lat[0], q_pe[0].astype(cfg.compute_dtype)], -1)
                ctx_lat = _mla_decode.mla_flash_decode(
                    q_full, cache.value, pos + 1, lat, scale)[None].astype(
                    cfg.compute_dtype)
            else:
                scores = (jnp.einsum("sbnl,tbl->bnst", q_lat, c_lat,
                                     preferred_element_type=jnp.float32)
                          + jnp.einsum("sbnd,tbd->bnst",
                                       q_pe.astype(cfg.compute_dtype), c_pe,
                                       preferred_element_type=jnp.float32)
                          ) * scale
                jpos = jnp.arange(max_len)[None, :]
                ipos = pos + jnp.arange(s)[:, None]
                scores = jnp.where(jpos > ipos, -1e9, scores)
                probs = jax.nn.softmax(scores, axis=-1)
                # weighted latent out, THEN expand through W_v (absorbed)
                ctx_lat = jnp.einsum("bnst,tbl->sbnl",
                                     probs.astype(cfg.compute_dtype), c_lat,
                                     preferred_element_type=jnp.float32
                                     ).astype(cfg.compute_dtype)
            ctx = jnp.einsum("sbnl,lnd->sbnd", ctx_lat, w_v,
                             preferred_element_type=jnp.float32)
            ctx = ctx.reshape(s, b, n_local * vd).astype(cfg.compute_dtype)
            return RowParallelLinear(
                input_size=cfg.num_heads * vd, output_size=cfg.hidden_size,
                input_is_parallel=True, bias=False,
                params_dtype=cfg.params_dtype, name="o")(ctx)


class _SwiGLU(nn.Module):
    config: MLAConfig
    ffn: Optional[int] = None  # None -> config.ffn_hidden_size

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ffn = self.ffn or cfg.ffn_hidden_size
        x = x.astype(cfg.compute_dtype)
        gate_up = ColumnParallelLinear(
            input_size=cfg.hidden_size, output_size=2 * ffn,
            gather_output=False, bias=False,
            params_dtype=cfg.params_dtype, name="gate_up")(x)
        gate, up = jnp.split(gate_up.astype(jnp.float32), 2, axis=-1)
        h = (jax.nn.silu(gate) * up).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=ffn, output_size=cfg.hidden_size,
            input_is_parallel=True, bias=False,
            params_dtype=cfg.params_dtype, name="down")(h)


class DeepseekBlock(nn.Module):
    config: MLAConfig
    layer_idx: int = 0

    def _is_moe(self):
        cfg = self.config
        return (cfg.n_routed_experts is not None
                and self.layer_idx >= cfg.first_k_dense_replace)

    @nn.compact
    def __call__(self, h, position_ids=None, mode="train"):
        cfg = self.config
        x = _norm(cfg, "input_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        h = h + MLAAttention(cfg, name="self_attn")(
            x, position_ids, mode=mode).astype(h.dtype)
        x = _norm(cfg, "post_attn_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        if not self._is_moe():
            return h + _SwiGLU(cfg, name="mlp")(x).astype(h.dtype)
        from apex_tpu.transformer.moe import SwitchMLP

        E, k = cfg.n_routed_experts, cfg.moe_top_k
        routed = SwitchMLP(
            hidden_size=cfg.hidden_size,
            ffn_hidden_size=cfg.moe_intermediate_size,
            num_experts=E, top_k=k,
            # default: dropless (E/k), the HF-parity semantics
            capacity_factor=(cfg.moe_capacity_factor
                             if cfg.moe_capacity_factor is not None
                             else float(E) / k),
            dispatch_mode=cfg.moe_dispatch_mode,
            router_type="top_k", activation="swiglu",
            normalize_topk=cfg.norm_topk_prob,
            params_dtype=cfg.params_dtype,
            compute_dtype=cfg.compute_dtype,
            warn_on_dropped_losses=False, name="mlp")(x)
        # scaling the combined routed output == scaling every gate
        out = routed * jnp.asarray(cfg.routed_scaling_factor, routed.dtype)
        if cfg.n_shared_experts:
            out = out + _SwiGLU(
                cfg, ffn=cfg.n_shared_experts * cfg.moe_intermediate_size,
                name="shared_mlp")(x)
        return h + out.astype(h.dtype)


class DeepseekModel(nn.Module):
    """DeepSeek-V2-style causal LM on MLA. Token ids [b, s] ->
    [b, s, vocab/tp] logits. Configs with ``n_routed_experts`` run
    greedy-gate MoE layers (fine-grained experts on SwitchMLP + shared
    expert) from ``first_k_dense_replace`` onward. Dropless serving
    (the default) uses the ragged grouped-matmul dispatch — linear in
    tokens, zero capacity padding; ``moe_capacity_factor`` caps it for
    training (scatter dispatch, dropped tokens ride the residual)."""

    config: MLAConfig

    @nn.compact
    def __call__(self, tokens, position_ids=None, mode="train"):
        cfg = self.config
        h = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            params_dtype=cfg.params_dtype, name="embed_tokens")(tokens)
        h = h.astype(cfg.compute_dtype).transpose(1, 0, 2)  # [s, b, h]
        pos = (position_ids.transpose(1, 0)
               if position_ids is not None else None)
        for i in range(cfg.num_layers):
            h = DeepseekBlock(cfg, layer_idx=i, name=f"layer_{i}")(
                h, pos, mode=mode)
        h = _norm(cfg, "final_norm")(h.astype(jnp.float32))
        h = copy_to_tensor_model_parallel_region(
            h.astype(cfg.compute_dtype))
        tp = get_tensor_model_parallel_world_size()
        head = self.param("lm_head", nn.initializers.normal(0.02),
                          (cfg.hidden_size, divide(cfg.vocab_size, tp)),
                          cfg.params_dtype)
        logits = jnp.einsum("sbh,hv->sbv", h,
                            head.astype(cfg.compute_dtype),
                            preferred_element_type=jnp.float32)
        return logits.transpose(1, 0, 2)

    def decode_prefill(self, tokens):
        """Latent-cache decode, phase 1 (apply with mutable=["cache"])."""
        return self(tokens, mode="prefill")

    def decode_step(self, tokens):
        """Latent-cache decode, phase 2 (single-token extension)."""
        return self(tokens, mode="step")


class _RawWeight(nn.Module):
    """Parameter-only scope: creates/looks up ``<name>/weight`` with the
    same shape the train-mode parallel linear uses, so decode and train
    modes share one param tree."""

    shape: tuple
    dtype: Any

    @nn.compact
    def __call__(self):
        return self.param("weight", nn.initializers.normal(0.02),
                          self.shape, self.dtype)


def mla_greedy_generate(model, params, prompt_tokens, max_new_tokens):
    """Greedy decode (full re-run per token — oracle path)."""
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    toks = jnp.asarray(prompt_tokens, jnp.int32)
    for _ in range(max_new_tokens):
        logits = model.apply({"params": params}, toks)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        nxt = jnp.argmax(full, -1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


@functools.lru_cache(maxsize=16)
def _mla_compiled_decode(model, max_new_tokens):
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    @jax.jit
    def prefill(params, prompt):
        logits, mut = model.apply(
            {"params": params}, prompt, mutable=["cache"],
            method=DeepseekModel.decode_prefill)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        return mut["cache"], jnp.argmax(full, -1).astype(jnp.int32)

    @jax.jit
    def decode_all(params, cache, first):
        def step(carry, _):
            cache, tok = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"], method=DeepseekModel.decode_step)
            full = gather_from_tensor_model_parallel_region(
                logits[:, -1, :])
            nxt = jnp.argmax(full, -1).astype(jnp.int32)
            return (mut["cache"], nxt), nxt
        (_, _), toks = jax.lax.scan(step, (cache, first), None,
                                    length=max_new_tokens - 1)
        return toks

    return prefill, decode_all


def mla_cached_generate(model, params, prompt_tokens, max_new_tokens):
    """Greedy decode on the LATENT cache (absorbed projections): the
    cache stores kv_lora_rank + qk_rope_head_dim floats per token per
    layer — shared across heads — instead of the 2*heads*(nope+rope)
    a conventional KV cache would. Token-exact vs
    :func:`mla_greedy_generate`, its oracle."""
    cfg = model.config
    plen = prompt_tokens.shape[1]
    if plen + max_new_tokens > cfg.max_decode_length:
        raise ValueError(
            f"prompt + max_new_tokens ({plen + max_new_tokens}) exceeds "
            f"max_decode_length ({cfg.max_decode_length})")
    toks = jnp.asarray(prompt_tokens, jnp.int32)
    if max_new_tokens == 0:
        return toks
    prefill, decode_all = _mla_compiled_decode(model, max_new_tokens)
    cache, first = prefill(params, toks)
    if max_new_tokens == 1:
        return jnp.concatenate([toks, first[:, None]], axis=1)
    rest = decode_all(params, cache, first)
    return jnp.concatenate([toks, first[:, None], rest.T], axis=1)
