"""T5 encoder-decoder language model (TPU-native).

Parity: the reference's encoder-decoder pipeline machinery is built for
Megatron T5 (relative-position-embedding groups in
apex/transformer/parallel_state.py:243-331; dual p2p shapes keyed off
``decoder_seq_length`` in
apex/transformer/pipeline_parallel/schedules/fwd_bwd_pipelining_without_interleaving.py:29-86).
This module supplies the *model family* those mechanics exist for: a real
T5 — relative position bias with log-spaced buckets (bidirectional for the
encoder, causal for the decoder), scale-only RMS layernorm, bias-free
linears, unscaled attention scores (T5 folds 1/sqrt(d) into init), relu or
gated-gelu FFN, tied or untied LM head with the d_model**-0.5 tied-head
rescale — on the same tensor-parallel primitives as the GPT/BERT families
(column/row-parallel projections, vocab-parallel embedding).

Encoder and decoder are exposed both fused (``__call__``) and as separate
``encode`` / ``decode_hidden`` / ``head`` / ``decode_from_memory``
methods so pipeline split-rank stages and two-phase generation can drive
each side independently.
"""

import dataclasses
import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_rank,
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64          # per-head dim, decoupled from d_model/num_heads
    d_ff: int = 2048
    num_layers: int = 6             # encoder depth
    num_decoder_layers: Optional[int] = None  # None -> num_layers
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"  # or "gated-gelu" (t5 v1.1)
    tie_word_embeddings: bool = True
    # KV-cache window for incremental decoding (relative positions put
    # no hard limit on T5 lengths; this bounds only the decode cache)
    max_decode_length: int = 512
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    activation_checkpointing: bool = False

    def __post_init__(self):
        if self.feed_forward_proj not in ("relu", "gated-gelu"):
            raise ValueError(
                f"unknown feed_forward_proj {self.feed_forward_proj!r}; "
                f"expected 'relu' or 'gated-gelu'")
        if self.num_heads < 1:
            raise ValueError(f"num_heads ({self.num_heads}) must be >= 1")

    @property
    def decoder_layers(self):
        return (self.num_decoder_layers if self.num_decoder_layers
                is not None else self.num_layers)

    @property
    def inner_dim(self):
        return self.num_heads * self.d_kv


def relative_position_bucket(relative_position, bidirectional,
                             num_buckets=32, max_distance=128):
    """Map key-minus-query offsets to T5's bias buckets.

    Half the buckets cover exact small offsets, the other half are
    log-spaced out to ``max_distance`` (beyond which everything shares the
    last bucket). Bidirectional (encoder) splits the budget between
    negative and positive offsets; causal (decoder) buckets only the
    lookback direction. Matches the T5 paper's assignment (and HF's
    `_relative_position_bucket`) so converted checkpoints reproduce
    logits exactly.
    """
    rel = relative_position
    bucket_offset = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        bucket_offset = jnp.where(rel > 0, num_buckets, 0)
        n = jnp.abs(rel)
    else:
        n = jnp.maximum(-rel, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # log-spaced: max_exact..num_buckets-1 over max_exact..max_distance
    nf = jnp.maximum(n, 1).astype(jnp.float32)
    large = max_exact + (
        jnp.log(nf / max_exact) / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return bucket_offset + jnp.where(is_small, n, large)


class _RelativeBias(nn.Module):
    """Per-head relative position bias table. The full
    [num_buckets, num_heads] table is replicated; each tp rank reads the
    bias rows for its contiguous head slice (same head layout as the
    column-parallel q/k/v shards)."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_len, k_len, q_offset=0):
        cfg = self.config
        table = self.param(
            "rel_attn_bias",
            nn.initializers.normal(0.02),
            (cfg.relative_attention_num_buckets, cfg.num_heads),
            cfg.params_dtype)
        ctx = q_offset + jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = relative_position_bucket(
            mem - ctx, self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance)
        bias = table[buckets]  # [q, k, heads]
        tp = get_tensor_model_parallel_world_size()
        if tp > 1:
            n_local = divide(cfg.num_heads, tp)
            rank = get_tensor_model_parallel_rank()
            bias = jax.lax.dynamic_slice_in_dim(
                bias, rank * n_local, n_local, axis=2)
        return bias.transpose(2, 0, 1).astype(jnp.float32)  # [n, q, k]


class T5Attention(nn.Module):
    """Self- or cross-attention with column-parallel q/k/v and
    row-parallel output, T5 conventions: no bias terms, no 1/sqrt(d)
    score scaling, additive per-head position bias on self-attention."""

    config: T5Config
    causal: bool = False
    cross: bool = False  # encoder-decoder attention (memory K/V)

    @nn.compact
    def __call__(self, x_q, x_kv=None, position_bias=None,
                 attention_mask=None, mode="train", pos=None):
        """``mode`` (static): 'train' — full attention; 'prefill' —
        decode with cache writes (self-attn K/V appended at ``pos``,
        cross-attn K/V of the memory computed once and stored);
        'step' — decode reading the caches (cross projections are never
        re-applied: this trace doesn't touch their params at all)."""
        cfg = self.config
        tp = get_tensor_model_parallel_world_size()
        n_local = divide(cfg.num_heads, tp)
        d = cfg.d_kv
        sq, b, _ = x_q.shape
        cross = self.cross
        decode = mode in ("prefill", "step")

        def proj(name, src):
            return ColumnParallelLinear(
                input_size=cfg.d_model, output_size=cfg.inner_dim,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name=name)(src)

        q = proj("q", x_q).reshape(sq, b, n_local, d)

        kv_mask = attention_mask
        if not decode:
            src = x_q if not cross else x_kv
            skv = src.shape[0]
            k = proj("k", src).reshape(skv, b, n_local, d)
            v = proj("v", src).reshape(skv, b, n_local, d)
            causal_from = jnp.arange(sq)[:, None] if self.causal else None
        elif cross:
            if mode == "prefill":
                skv = x_kv.shape[0]
                k = proj("k", x_kv).reshape(skv, b, n_local, d)
                v = proj("v", x_kv).reshape(skv, b, n_local, d)
                ck = self.variable("cache", "cross_key",
                                   lambda: k.astype(cfg.compute_dtype))
                cv = self.variable("cache", "cross_value",
                                   lambda: v.astype(cfg.compute_dtype))
                ck.value = k.astype(cfg.compute_dtype)
                cv.value = v.astype(cfg.compute_dtype)
            else:
                if not self.has_variable("cache", "cross_key"):
                    # reachable now that cross-ness is declared on the
                    # module (an empty cache dict means no prefill ran)
                    raise ValueError(
                        "T5 decode_step before decode_prefill: the "
                        "cross-attention cache is empty")
                k = self.variable("cache", "cross_key", None).value
                v = self.variable("cache", "cross_value", None).value
            causal_from = None  # encoder memory is fully visible
        else:
            # causal self-attention over the cache prefix
            if pos is None:
                raise ValueError("decode self-attention needs pos")
            max_len = cfg.max_decode_length
            k_new = proj("k", x_q).reshape(sq, b, n_local, d)
            v_new = proj("v", x_q).reshape(sq, b, n_local, d)
            ck = self.variable(
                "cache", "cached_key", jnp.zeros,
                (max_len, b, n_local, d), cfg.compute_dtype)
            cv = self.variable(
                "cache", "cached_value", jnp.zeros,
                (max_len, b, n_local, d), cfg.compute_dtype)
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k_new.astype(cfg.compute_dtype), (pos, 0, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v_new.astype(cfg.compute_dtype), (pos, 0, 0, 0))
            k, v = ck.value, cv.value
            causal_from = pos + jnp.arange(sq)[:, None]
            kv_mask = None  # decoder tokens are unpadded by contract

        # T5 leaves scores unscaled (the 1/sqrt(d) lives in init)
        scores = jnp.einsum("qbnd,kbnd->bnqk",
                            q.astype(cfg.compute_dtype),
                            k.astype(cfg.compute_dtype),
                            preferred_element_type=jnp.float32)
        if position_bias is not None:
            scores = scores + position_bias[None]  # [n, q, k] broadcast
        if causal_from is not None:
            j = jnp.arange(k.shape[0])[None, :]
            scores = jnp.where(j > causal_from, -1e9, scores)
        if kv_mask is not None:
            # [b, k] padding mask: True/1 = attend
            scores = jnp.where(
                kv_mask.astype(bool)[:, None, None, :], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bnqk,kbnd->qbnd",
                         probs.astype(cfg.compute_dtype),
                         v.astype(cfg.compute_dtype),
                         preferred_element_type=jnp.float32)
        ctx = ctx.reshape(sq, b, n_local * d).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=cfg.inner_dim, output_size=cfg.d_model,
            input_is_parallel=True, bias=False,
            params_dtype=cfg.params_dtype, name="o")(ctx)


class T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = x.astype(cfg.compute_dtype)
        if cfg.feed_forward_proj == "gated-gelu":
            gate = ColumnParallelLinear(
                input_size=cfg.d_model, output_size=cfg.d_ff,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="wi_0")(x)
            up = ColumnParallelLinear(
                input_size=cfg.d_model, output_size=cfg.d_ff,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="wi_1")(x)
            # HF gated-gelu gates with the tanh approximation (gelu_new)
            h = (jax.nn.gelu(gate.astype(jnp.float32), approximate=True)
                 * up.astype(jnp.float32)).astype(cfg.compute_dtype)
        else:
            h = ColumnParallelLinear(
                input_size=cfg.d_model, output_size=cfg.d_ff,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype, name="wi")(x)
            h = jax.nn.relu(h.astype(jnp.float32)).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=cfg.d_ff, output_size=cfg.d_model,
            input_is_parallel=True, bias=False,
            params_dtype=cfg.params_dtype, name="wo")(h)


def _norm(cfg, name):
    return FusedRMSNorm(normalized_shape=cfg.d_model,
                        eps=cfg.layer_norm_epsilon,
                        param_dtype=jnp.float32, name=name)


class T5Block(nn.Module):
    """Pre-RMSNorm residual block: self-attn [+ cross-attn] + FFN."""

    config: T5Config
    has_cross: bool = False
    causal: bool = False

    @nn.compact
    def __call__(self, h, memory=None, position_bias=None,
                 self_mask=None, cross_mask=None, mode="train", pos=None):
        cfg = self.config
        x = _norm(cfg, "self_attn_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        h = h + T5Attention(cfg, causal=self.causal, name="self_attn")(
            x, None, position_bias, self_mask, mode=mode,
            pos=pos).astype(h.dtype)
        if self.has_cross:
            x = _norm(cfg, "cross_attn_norm")(h.astype(jnp.float32)).astype(
                cfg.compute_dtype)
            # cross-attention carries no relative bias (T5 convention)
            h = h + T5Attention(cfg, causal=False, cross=True,
                                name="cross_attn")(
                x, memory, None, cross_mask, mode=mode).astype(h.dtype)
        x = _norm(cfg, "ffn_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        return h + T5FFN(cfg, name="ffn")(x).astype(h.dtype)


class T5Encoder(nn.Module):
    """Embedded tokens -> encoder memory [s, b, d_model] (fp32 normed)."""

    config: T5Config

    @nn.compact
    def __call__(self, h, attention_mask=None):
        cfg = self.config
        bias = _RelativeBias(cfg, bidirectional=True,
                             name="relative_bias")(h.shape[0], h.shape[0])
        block = T5Block
        if cfg.activation_checkpointing:
            block = nn.checkpoint(T5Block, static_argnums=())
        for i in range(cfg.num_layers):
            h = block(cfg, has_cross=False, causal=False,
                      name=f"block_{i}")(h, None, bias, attention_mask,
                                         None)
        return _norm(cfg, "final_norm")(h.astype(jnp.float32))


class T5Decoder(nn.Module):
    """Embedded decoder tokens + encoder memory -> pre-head hidden
    [s, b, d_model] (fp32 normed).

    ``mode='prefill'/'step'`` runs the KV-cache incremental path: a
    stack-level ``pos`` counter offsets the relative-position bias
    (computed against the full cache window), self-attention appends to
    per-block caches, and cross-attention K/V are computed from the
    memory once at prefill, then read back — a step trace never touches
    the cross k/v projection weights."""

    config: T5Config

    @nn.compact
    def __call__(self, h, memory=None, self_mask=None, cross_mask=None,
                 mode="train"):
        cfg = self.config
        rel = _RelativeBias(cfg, bidirectional=False, name="relative_bias")
        pos = None
        if mode in ("prefill", "step"):
            ctr = self.variable("cache", "pos",
                                lambda: jnp.zeros((), jnp.int32))
            pos = jnp.zeros((), jnp.int32) if mode == "prefill" \
                else ctr.value
            bias = rel(h.shape[0], cfg.max_decode_length, q_offset=pos)
            ctr.value = pos + h.shape[0]
        else:
            bias = rel(h.shape[0], h.shape[0])
        block = T5Block
        if cfg.activation_checkpointing and mode == "train":
            block = nn.checkpoint(T5Block, static_argnums=())
        for i in range(cfg.decoder_layers):
            if mode == "train":
                # keyword-free call: nn.checkpoint traces every arg and
                # a static mode string must not reach it
                h = block(cfg, has_cross=True, causal=True,
                          name=f"block_{i}")(h, memory, bias, self_mask,
                                             cross_mask)
            else:
                h = T5Block(cfg, has_cross=True, causal=True,
                            name=f"block_{i}")(h, memory, bias, self_mask,
                                               cross_mask, mode=mode,
                                               pos=pos)
        return _norm(cfg, "final_norm")(h.astype(jnp.float32))


class T5Model(nn.Module):
    """Conditional-generation T5. ``__call__(enc_tokens, dec_tokens)``
    with [b, s] int ids returns [b, s_dec, vocab/tp] logits. ``encode``
    and ``decode_from_memory`` expose the two halves for pipeline
    split-rank stages and two-phase generation."""

    config: T5Config

    def setup(self):
        cfg = self.config
        self.shared = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.d_model,
            params_dtype=cfg.params_dtype, name="shared")
        self.encoder = T5Encoder(cfg, name="encoder")
        self.decoder = T5Decoder(cfg, name="decoder")
        if not cfg.tie_word_embeddings:
            tp = get_tensor_model_parallel_world_size()
            self.lm_head = self.param(
                "lm_head", nn.initializers.normal(0.02),
                (cfg.d_model, divide(cfg.vocab_size, tp)),
                cfg.params_dtype)

    def _embed(self, tokens):
        # [b, s] -> [s, b, d_model] (seq-major, Megatron layout)
        return self.shared(tokens).astype(
            self.config.compute_dtype).transpose(1, 0, 2)

    def encode(self, enc_tokens, enc_mask=None):
        return self.encoder(self._embed(enc_tokens), enc_mask)

    def decode_hidden(self, dec_tokens, memory, enc_mask=None):
        """Decoder stack only (pre-head [s, b, d_model]) — the pipeline
        split-rank stage payload; the head lives in ``head`` so the
        schedule's loss_func can apply it on the last rank."""
        return self.decoder(self._embed(dec_tokens),
                            memory.astype(self.config.compute_dtype),
                            cross_mask=enc_mask)

    def head(self, h):
        cfg = self.config
        h = copy_to_tensor_model_parallel_region(
            h.astype(cfg.compute_dtype))
        if cfg.tie_word_embeddings:
            # tied head contracts with the shared table after the T5
            # rescale (HF: sequence_output * d_model**-0.5)
            h = h * jnp.asarray(cfg.d_model ** -0.5, h.dtype)
            logits = self.shared.attend(h)
        else:
            logits = jnp.einsum(
                "sbh,hv->sbv", h, self.lm_head.astype(cfg.compute_dtype),
                preferred_element_type=jnp.float32)
        return logits.transpose(1, 0, 2)  # [b, s, vocab/tp]

    def decode_from_memory(self, dec_tokens, memory, enc_mask=None):
        return self.head(self.decode_hidden(dec_tokens, memory, enc_mask))

    def decode_prefill(self, dec_tokens, memory, enc_mask=None):
        """KV-cache decode, phase 1: run the given decoder prefix,
        filling the self-attention caches and computing the
        cross-attention K/V from ``memory`` once. Apply with
        ``mutable=["cache"]``. Returns [b, s, vocab/tp] logits."""
        h = self.decoder(self._embed(dec_tokens),
                         memory.astype(self.config.compute_dtype),
                         cross_mask=enc_mask, mode="prefill")
        return self.head(h)

    def decode_step(self, dec_tokens, enc_mask=None):
        """KV-cache decode, phase 2: extend by ``dec_tokens`` (usually
        one token) against the caches; the encoder memory is NOT needed
        (cross K/V are read back, their projections never re-applied).
        Apply with ``mutable=["cache"]``."""
        h = self.decoder(self._embed(dec_tokens), None,
                         cross_mask=enc_mask, mode="step")
        return self.head(h)

    def __call__(self, enc_tokens, dec_tokens, enc_mask=None):
        memory = self.encode(enc_tokens, enc_mask)
        return self.decode_from_memory(dec_tokens, memory, enc_mask)


def t5_greedy_generate(model, params, enc_tokens, max_new_tokens,
                       decoder_start_token_id=0, enc_mask=None):
    """Greedy decode: encode once, then argmax one token at a time with a
    full decoder re-run per step (bounded unrolled loop — token-exact
    oracle path; the KV-cache fast path is the decoder-only family's
    ``generate``)."""
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    b = enc_tokens.shape[0]
    memory = model.apply({"params": params}, enc_tokens, enc_mask,
                         method=T5Model.encode)
    dec = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    for _ in range(max_new_tokens):
        logits = model.apply({"params": params}, dec, memory, enc_mask,
                             method=T5Model.decode_from_memory)
        # vocab-parallel shards -> full vocabulary before argmax (no-op
        # at tp=1 / unbound axis)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        nxt = jnp.argmax(full, axis=-1).astype(jnp.int32)
        dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
    return dec


@functools.lru_cache(maxsize=16)
def _t5_compiled_decode(model, max_new_tokens, has_mask,
                        eos_token_id=None, pad_token_id=0):
    """jitted prefill + scan-decode for :func:`t5_cached_generate`,
    cached per (model, length, maskedness) so a serving loop compiles
    once (same pattern as generation.py's ``_compiled``). ``enc_mask``
    is threaded as an argument — closures would defeat the cache."""
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    @jax.jit
    def prefill(params, start, memory, enc_mask):
        # no pre-built cache: flax CREATES the 'cache' collection under
        # mutable — so a decode_step without a prefilled cache has no
        # cross_key variable and hits the loud guard instead of silently
        # attending over zeros
        logits, mut = model.apply(
            {"params": params}, start, memory,
            enc_mask if has_mask else None,
            mutable=["cache"], method=T5Model.decode_prefill)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        return mut["cache"], jnp.argmax(full, -1).astype(jnp.int32)

    @jax.jit
    def decode_all(params, cache, first, enc_mask):
        done0 = (jnp.zeros(first.shape, bool) if eos_token_id is None
                 else first == eos_token_id)

        def step(carry, _):
            cache, tok, done = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                enc_mask if has_mask else None,
                mutable=["cache"], method=T5Model.decode_step)
            full = gather_from_tensor_model_parallel_region(
                logits[:, -1, :])
            nxt = jnp.argmax(full, -1).astype(jnp.int32)
            if eos_token_id is not None:
                # finished rows extend with pad (HF generate semantics)
                nxt = jnp.where(done, pad_token_id, nxt)
                done = done | (nxt == eos_token_id)
            return (mut["cache"], nxt, done), nxt
        (_, _, _), toks = jax.lax.scan(step, (cache, first, done0), None,
                                       length=max_new_tokens - 1)
        return toks  # [T-1, b]

    return prefill, decode_all


def t5_cached_generate(model, params, enc_tokens, max_new_tokens,
                       decoder_start_token_id=0, enc_mask=None,
                       eos_token_id=None, pad_token_id=0):
    """Greedy decode on the KV-cache path: encode once, prefill with the
    start token, then one jitted single-token step per new token under
    ``lax.scan`` — per-step work is O(1) in the generated length (vs the
    full decoder re-run of :func:`t5_greedy_generate`, its oracle)."""
    start = _t5_decode_precheck(model, enc_tokens, max_new_tokens,
                                decoder_start_token_id)
    if max_new_tokens == 0:
        return start
    return _t5_run_decode(model, params, enc_tokens, enc_mask, start,
                          max_new_tokens, has_mask=enc_mask is not None,
                          eos_token_id=eos_token_id,
                          pad_token_id=pad_token_id)


def _t5_decode_precheck(model, enc_tokens, max_new_tokens,
                        decoder_start_token_id):
    """Shared capacity check + start column for the tp=1 and tp>1 paths.
    Slots written: 1 (prefill, the start token) + max_new_tokens - 1
    steps (the last generated token is never fed back)."""
    cfg = model.config
    if max_new_tokens > cfg.max_decode_length:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) exceeds "
            f"max_decode_length ({cfg.max_decode_length})")
    return jnp.full((enc_tokens.shape[0], 1), decoder_start_token_id,
                    jnp.int32)


def _t5_run_decode(model, params, enc_tokens, mask, start,
                   max_new_tokens, has_mask, eos_token_id=None,
                   pad_token_id=0):
    """encode -> prefill -> scan-decode -> [start | tokens]; the single
    orchestration body both the tp=1 entry and the shard_map'd tp body
    run (mask may be None at tp=1 — jit treats it as an empty pytree;
    has_mask already specializes the trace)."""
    prefill, decode_all = _t5_compiled_decode(model, max_new_tokens,
                                              has_mask, eos_token_id,
                                              pad_token_id)
    memory = model.apply({"params": params}, enc_tokens,
                         mask if has_mask else None,
                         method=T5Model.encode)
    cache, first = prefill(params, start, memory, mask)
    if max_new_tokens == 1:
        return jnp.concatenate([start, first[:, None]], axis=1)
    toks = decode_all(params, cache, first, mask)
    return jnp.concatenate([start, first[:, None], toks.T], axis=1)


@functools.lru_cache(maxsize=16)
def _t5_compiled_beam(model, max_new_tokens, num_beams, has_mask,
                      eos_token_id, pad_token_id, length_penalty):
    """jitted encode-side beam search for :func:`t5_beam_generate`
    (same caching discipline as ``_t5_compiled_decode``)."""
    from apex_tpu.models.encdec_beam import (
        beam_search_cached,
        tile_cache_for_beams,
    )
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    @jax.jit
    def run(params, start, memory, enc_mask):
        logits, mut = model.apply(
            {"params": params}, start, memory,
            enc_mask if has_mask else None,
            mutable=["cache"], method=T5Model.decode_prefill)
        first = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        cache = tile_cache_for_beams(mut["cache"], num_beams)
        mask_k = (jnp.repeat(enc_mask, num_beams, axis=0) if has_mask
                  else None)

        def step_fn(cache, tok):
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mask_k, mutable=["cache"], method=T5Model.decode_step)
            return gather_from_tensor_model_parallel_region(
                logits[:, -1, :]), mut["cache"]

        return beam_search_cached(
            step_fn, cache, first, num_beams=num_beams,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, length_penalty=length_penalty)

    return run


def t5_beam_generate(model, params, enc_tokens, max_new_tokens,
                     num_beams=4, decoder_start_token_id=0, enc_mask=None,
                     eos_token_id=None, pad_token_id=0,
                     length_penalty=1.0):
    """Beam search on the T5 KV-cache decode path (HF generate
    semantics — see models/encdec_beam.py). Encode once, prefill the
    start token, tile the caches per beam, then one jitted step per new
    token with per-beam cache reordering. Returns ([b, 1 + max_new]
    sequences incl the start column, [b] final scores)."""
    start = _t5_decode_precheck(model, enc_tokens, max_new_tokens,
                                decoder_start_token_id)
    if max_new_tokens == 0:
        return start, jnp.zeros((enc_tokens.shape[0],), jnp.float32)
    has_mask = enc_mask is not None
    run = _t5_compiled_beam(model, max_new_tokens, num_beams, has_mask,
                            eos_token_id, pad_token_id,
                            float(length_penalty))
    memory = model.apply({"params": params}, enc_tokens,
                         enc_mask if has_mask else None,
                         method=T5Model.encode)
    seqs, scores = run(params, start, memory, enc_mask)
    return jnp.concatenate([start, seqs], axis=1), scores


def tensor_parallel_t5_generate(model, stacked_params, enc_tokens,
                                max_new_tokens, *, mesh=None,
                                decoder_start_token_id=0, enc_mask=None,
                                eos_token_id=None, pad_token_id=0):
    """Greedy KV-cache T5 decoding under tensor parallelism: the whole
    encode + prefill + scan-decode runs inside ONE shard_map over the
    'tp' mesh axis (same pattern as the decoder-only family's
    ``tensor_parallel_generate``). Vocab-parallel logits are gathered per
    step, so every rank argmaxes the full vocabulary and emits identical
    tokens. ``stacked_params`` is the leading-[tp] layout from
    :func:`apex_tpu.models.tp_split.split_t5_params_for_tp`."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    mesh = mesh or parallel_state.get_mesh()
    start = _t5_decode_precheck(model, enc_tokens, max_new_tokens,
                                decoder_start_token_id)
    if max_new_tokens == 0:
        return start
    has_mask = enc_mask is not None

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("tp"), P(), P()), out_specs=P(),
                       check_vma=False)
    def go(sp, enc, mask):
        p = jax.tree_util.tree_map(lambda a: a[0], sp)
        return _t5_run_decode(model, p, enc, mask, start,
                              max_new_tokens, has_mask,
                              eos_token_id=eos_token_id,
                              pad_token_id=pad_token_id)

    mask_arg = (enc_mask if has_mask
                else jnp.zeros((0,), jnp.int32))  # spec placeholder
    return go(stacked_params, enc_tokens, mask_arg)


def t5_loss_fn(vocab_parallel_logits, labels, loss_mask=None):
    """Mean per-token vocab-parallel CE over decoder positions."""
    from apex_tpu.transformer.tensor_parallel import (
        vocab_parallel_cross_entropy,
    )

    losses = vocab_parallel_cross_entropy(vocab_parallel_logits, labels)
    if loss_mask is not None:
        return jnp.sum(losses * loss_mask) / jnp.maximum(
            jnp.sum(loss_mask), 1.0)
    return jnp.mean(losses)
