"""Megatron-style parallel transformer blocks (TPU-native).

Parity: reference apex/transformer/testing/standalone_transformer_lm.py —
``ParallelMLP`` (h -> 4h column-parallel -> gelu -> 4h -> h row-parallel),
``ParallelAttention`` (column-parallel QKV, core attention with
FusedScaleMaskSoftmax, row-parallel output projection),
``ParallelTransformerLayer`` (pre-LN residual blocks). Re-designed for TPU:
bf16 matmuls on the MXU with fp32 layernorm/softmax, sequence-parallel
collectives on the seq dim, flash attention (Pallas) for the core when
enabled.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
)


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Frequency-rescaled RoPE (HF modeling_rope_utils semantics).

    ``rope_type="linear"`` divides every inverse frequency by ``factor``
    (position interpolation). ``rope_type="llama3"`` (Llama-3.1) keeps
    wavelengths shorter than ``original_max/high_freq_factor``, divides
    those longer than ``original_max/low_freq_factor`` by ``factor``,
    and smoothly interpolates in between
    (_compute_llama3_parameters). All-scalar and frozen, so
    TransformerConfig remains hashable for static jit arguments."""

    rope_type: str = "llama3"
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


def _scale_rope_freqs(inv, scaling: RopeScaling):
    import math

    if scaling.rope_type == "linear":
        return inv / scaling.factor
    if scaling.rope_type != "llama3":
        raise ValueError(f"unknown rope_type {scaling.rope_type!r}")
    old_len = scaling.original_max_position_embeddings
    low_wavelen = old_len / scaling.low_freq_factor
    high_wavelen = old_len / scaling.high_freq_factor
    wavelen = 2 * math.pi / inv
    scaled = jnp.where(wavelen > low_wavelen, inv / scaling.factor, inv)
    smooth = ((old_len / wavelen - scaling.low_freq_factor)
              / (scaling.high_freq_factor - scaling.low_freq_factor))
    smoothed = ((1 - smooth) * scaled / scaling.factor + smooth * scaled)
    medium = (wavelen >= high_wavelen) & (wavelen <= low_wavelen)
    return jnp.where(medium, smoothed, scaled)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    ffn_hidden_size: Optional[int] = None
    vocab_size: int = 50257
    max_position_embeddings: int = 1024
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    layernorm_epsilon: float = 1e-5
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    sequence_parallel: bool = False
    use_flash_attention: bool = True
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    # Context parallelism: run the WHOLE model on sequence shards over
    # the 'cp' mesh axis (attention communicates; everything else is
    # per-token). Callers shard tokens/labels over cp and pass global
    # position_ids; see transformer/context_parallel. Algorithms:
    # "ring" (K/V ppermute around the ring — any head count) or
    # "ulysses" (two all_to_alls, full attention on heads/cp heads —
    # needs heads divisible by cp; cheaper when heads >= cp).
    context_parallel: bool = False
    context_parallel_algo: str = "ring"
    # Compile the layer stack as ONE lax.scan over stacked params instead
    # of unrolling n layers (compile time O(1) in depth — the unrolled
    # 24-layer GPT costs minutes of XLA time per bench variant). Params
    # get a leading [num_layers] axis under 'layers'; requires a uniform
    # stack (with MoE: moe_layer_freq == 1).
    scan_layers: bool = False
    # Per-layer activation recompute (reference tensor_parallel/random.py
    # checkpoint). ON by default for the reference's memory profile; turn
    # OFF when the model fits HBM without it — backward then reuses the
    # forward's activations instead of re-running every layer (~25-30%
    # fewer executed FLOPs per train step, the single biggest single-chip
    # MFU lever at GPT-2-345M scale).
    activation_checkpointing: bool = True
    # Mixture-of-experts (no reference equivalent; SURVEY.md §2.3 note).
    # None -> dense ParallelMLP everywhere. Every ``moe_layer_freq``-th
    # layer (starting at layer 0) becomes a SwitchMLP with this many
    # global experts, sharded over the 'ep' mesh axis.
    num_moe_experts: Optional[int] = None
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_layer_freq: int = 1
    moe_jitter_eps: float = 0.0
    moe_router_type: str = "top_k"  # or "expert_choice"
    moe_aux_loss_coeff: float = 1e-2
    moe_z_loss_coeff: float = 0.0
    # auto -> ragged grouped matmuls when dropless on one ep rank (the
    # converted-Mixtral serving shape), scatter otherwise; "einsum" keeps
    # the dense [T,E,C] one-hot formulation (see moe/layer.py SwitchMLP).
    moe_dispatch_mode: str = "auto"
    # renormalize the selected top-k gates to sum to 1 (Mixtral); False
    # keeps raw softmax mass (Qwen2-MoE norm_topk_prob=false)
    moe_normalize_topk: bool = True
    # Always-on shared expert beside the routed set (Qwen2-MoE block:
    # out = routed + sigmoid(gate(x)) * shared(x)); None -> none.
    moe_shared_expert_size: Optional[int] = None
    moe_shared_expert_gated: bool = True
    # Modern-LLM (Llama-family) knobs — beyond the reference, which is
    # GPT-2/BERT-era: grouped-query attention (fewer K/V head groups),
    # rotary position embeddings, SwiGLU MLPs, RMSNorm blocks.
    num_query_groups: Optional[int] = None  # None -> MHA (groups == heads)
    position_embedding_type: str = "learned"  # or "rope"
    rotary_base: float = 10000.0
    # Long-context RoPE frequency rescaling (Llama-3.1 "llama3" or
    # position-interpolation "linear"); None -> unscaled frequencies.
    rope_scaling: Optional[RopeScaling] = None
    # Gemma-3: layers whose sliding window applies use THIS rope base
    # and skip rope_scaling (local 10k vs global 1M + linear scaling);
    # None -> every layer uses rotary_base/rope_scaling.
    rotary_base_local: Optional[float] = None
    # SmolLM3 NoPE alternation: every interval-th layer ((i+1) % N == 0)
    # applies NO rotary embedding at all. 0 -> rope on every layer.
    no_rope_layer_interval: int = 0
    # Query/key RMSNorm before rope: "projection" (OLMoE — one norm over
    # the full flattened q / k projection output) or "head" (Qwen3 —
    # per-head over head_dim, tensor-parallel-safe). None -> off.
    qk_norm: Optional[str] = None
    # DBRX: clamp the QKV projection outputs to [-clip, clip]
    # (elementwise, applied after the fused projection — identical to
    # HF's clamp of the fused Wqkv output). None -> no clamp.
    qkv_clip: Optional[float] = None
    # "gelu" is the tanh approximation (GPT-2 gelu_new); "gelu_exact"
    # the erf form (HF "gelu" — Falcon/NeoX default); "relu" (OPT);
    # "relu2" squared ReLU (Nemotron); "swiglu"/"geglu" are the gated
    # fused forms.
    activation: str = "gelu"
    # Scale token embeddings by this factor on entry (Gemma family uses
    # sqrt(hidden_size); the tied head contracts with the UNSCALED table).
    embedding_multiplier: Optional[float] = None
    # Per-head attention dim decoupled from hidden_size/num_heads (e.g.
    # gemma-7b: 256 vs 3072/16=192). None -> hidden_size // num_heads.
    head_dim: Optional[int] = None
    # GPT-NeoX/Pythia-family knobs: sum attention and MLP branches into
    # ONE residual (both read the pre-attn stream), and rotate only the
    # leading fraction of each head's dims (rotary_pct).
    parallel_residual: bool = False
    rotary_percent: float = 1.0
    # GPT-J rope convention: rotate interleaved even/odd pairs instead
    # of the rotate-half block form.
    rotary_interleaved: bool = False
    # Phi/Falcon-7b form of the parallel residual: ONE layernorm feeds
    # both branches (no post_attention_layernorm params).
    parallel_residual_shared_ln: bool = False
    # Phi ties a bias to the LM head projection (vocab-parallel sliced
    # with the head columns).
    lm_head_bias: bool = False
    # Mistral-style sliding-window attention: query i sees key j iff
    # 0 <= i - j < sliding_window (on top of causal). None -> full causal.
    sliding_window: Optional[int] = None
    # Alternating local/global attention (Gemma-2/3): the window applies
    # to layer i iff (i + 1) % pattern != 0 — every pattern-th layer runs
    # full causal attention (Gemma-2: pattern 2 -> even layers local;
    # Gemma-3: pattern 6). 1 -> every layer windowed (Mistral).
    sliding_window_pattern: int = 1
    # Gemma-2 tanh soft-capping: scores -> cap * tanh(scores / cap)
    # after the softmax scale, before masking (HF modeling_gemma2
    # eager_attention_forward). Takes the masked-softmax path — the
    # flash kernel has no softcap epilogue.
    attn_logit_softcapping: Optional[float] = None
    # Gemma-2: LM-head logits -> cap * tanh(logits / cap) (fp32),
    # applied per vocab-parallel shard (elementwise).
    final_logit_softcapping: Optional[float] = None
    # Decoupled softmax scale (Gemma-2 query_pre_attn_scalar): scores
    # are scaled by this value**-0.5 instead of kv_channels**-0.5
    # (gemma-2-27b: 144 vs head_dim 128). None -> kv_channels.
    query_pre_attn_scalar: Optional[float] = None
    # Gemma-2 "sandwich" residual form: each branch output is normed
    # BEFORE its residual add (x + post_norm(branch(pre_norm(x)))) —
    # adds post_self_attn_norm / post_mlp_norm params per layer.
    sandwich_norm: bool = False
    # False -> no input/pre-MLP norms: branches read the RAW residual
    # stream (OLMo-2 post-norm blocks: x + post_norm(branch(x))).
    # Requires sandwich_norm (a block with no norms at all is refused).
    pre_norm: bool = True
    # Granite muP-style scalars: each branch output is scaled before
    # its residual add (x + m * branch(...)), and LM logits are DIVIDED
    # by logits_scaling (HF modeling_granite "main diff with Llama").
    residual_multiplier: float = 1.0
    logits_scaling: float = 1.0
    normalization: str = "layernorm"  # or "rmsnorm"
    # BLOOM applies a layernorm directly after the token embeddings.
    embedding_layernorm: bool = False
    # Tie the LM head to the word-embedding table (reference
    # parallel_lm_logits ties by default). Off here because the SPMD
    # pipeline harness needs untied heads (first/last stages run the same
    # program but hold different params); single-program models (dp/tp/ep)
    # can and should tie.
    tie_word_embeddings: bool = False

    def __post_init__(self):
        if self.sliding_window is not None:
            if self.sliding_window < 1:
                raise ValueError(
                    f"sliding_window ({self.sliding_window}) must be >= 1")
            if self.attn_mask_type != AttnMaskType.causal:
                raise ValueError("sliding_window requires causal attention")
            if self.context_parallel:
                raise ValueError(
                    "sliding_window does not compose with context "
                    "parallelism (the ring/ulysses kernels run full "
                    "causal attention)")
        if self.sliding_window_pattern < 1:
            raise ValueError(
                f"sliding_window_pattern ({self.sliding_window_pattern}) "
                f"must be >= 1")
        if self.sliding_window_pattern > 1:
            if self.sliding_window is None:
                raise ValueError(
                    "sliding_window_pattern > 1 needs sliding_window set")
            if self.scan_layers:
                raise ValueError(
                    "scan_layers needs a uniform stack: alternating "
                    "local/global attention (sliding_window_pattern > 1) "
                    "cannot be scanned")
        if self.query_pre_attn_scalar is not None and self.context_parallel:
            raise ValueError(
                "query_pre_attn_scalar does not compose with context "
                "parallelism (the ring/ulysses kernels use the default "
                "1/sqrt(head_dim) softmax scale)")
        if self.attn_logit_softcapping is not None:
            if self.attn_logit_softcapping <= 0:
                raise ValueError(
                    f"attn_logit_softcapping "
                    f"({self.attn_logit_softcapping}) must be > 0")
            if self.context_parallel:
                raise ValueError(
                    "attn_logit_softcapping does not compose with context "
                    "parallelism (the ring/ulysses kernels carry no "
                    "softcap epilogue)")
        if self.qkv_clip is not None and self.qkv_clip <= 0:
            raise ValueError(f"qkv_clip ({self.qkv_clip}) must be > 0")
        if self.qk_norm not in (None, "projection", "head"):
            raise ValueError(
                f"unknown qk_norm {self.qk_norm!r}; expected "
                f"'projection' (OLMoE) or 'head' (Qwen3)")
        if self.no_rope_layer_interval:
            if self.no_rope_layer_interval < 2:
                raise ValueError(
                    f"no_rope_layer_interval "
                    f"({self.no_rope_layer_interval}) must be >= 2 (1 "
                    f"would disable rope everywhere — use "
                    f"position_embedding_type='learned'/'alibi' instead)")
            if self.position_embedding_type != "rope":
                raise ValueError("no_rope_layer_interval requires "
                                 "position_embedding_type='rope'")
            if self.scan_layers:
                raise ValueError(
                    "scan_layers needs a uniform stack: NoPE alternation "
                    "(no_rope_layer_interval) cannot be scanned")
        if self.rotary_base_local is not None and self.sliding_window is None:
            raise ValueError(
                "rotary_base_local needs sliding_window set (it applies "
                "to the windowed layers only)")
        if self.rope_scaling is not None:
            if self.position_embedding_type != "rope":
                raise ValueError("rope_scaling requires "
                                 "position_embedding_type='rope'")
            if self.rope_scaling.rope_type not in ("linear", "llama3"):
                raise ValueError(
                    f"unknown rope_type "
                    f"{self.rope_scaling.rope_type!r}; expected 'linear' "
                    f"or 'llama3'")
            if self.rope_scaling.factor < 1.0:
                raise ValueError(
                    f"rope_scaling.factor ({self.rope_scaling.factor}) "
                    f"must be >= 1")
        if (self.final_logit_softcapping is not None
                and self.final_logit_softcapping <= 0):
            raise ValueError(
                f"final_logit_softcapping "
                f"({self.final_logit_softcapping}) must be > 0")
        if self.sandwich_norm and self.parallel_residual:
            raise ValueError(
                "sandwich_norm and parallel_residual are mutually "
                "exclusive residual forms")
        if self.logits_scaling <= 0:
            raise ValueError(
                f"logits_scaling ({self.logits_scaling}) must be > 0 "
                f"(it divides the LM logits)")
        if not self.pre_norm and not self.sandwich_norm:
            # (parallel_residual is already excluded transitively: it is
            # mutually exclusive with the sandwich_norm required here)
            raise ValueError(
                "pre_norm=False (OLMo-2 post-norm blocks) requires "
                "sandwich_norm=True — a block with no norms at all "
                "is almost certainly a config mistake")
        if self.parallel_residual_shared_ln and not self.parallel_residual:
            raise ValueError(
                "parallel_residual_shared_ln requires parallel_residual")
        if self.lm_head_bias and self.tie_word_embeddings:
            raise ValueError(
                "lm_head_bias requires an untied head (the tied path "
                "contracts with the embedding table and has no bias)")
        if not 0.0 < self.rotary_percent <= 1.0:
            raise ValueError(
                f"rotary_percent ({self.rotary_percent}) must be in (0, 1]")
        if self.head_dim is not None:
            if self.head_dim < 1:
                raise ValueError(f"head_dim ({self.head_dim}) must be >= 1")
            if self.head_dim * self.num_attention_heads == self.hidden_size:
                # normalize the derived value to None so numerically
                # identical configs compare/serialize identically and
                # producers can pass head_dim through unconditionally
                object.__setattr__(self, "head_dim", None)
        if self.position_embedding_type not in ("learned", "rope",
                                                "alibi"):
            raise ValueError(
                f"unknown position_embedding_type "
                f"{self.position_embedding_type!r}; expected 'learned', "
                f"'rope' or 'alibi'")
        if self.position_embedding_type == "alibi" and self.context_parallel:
            raise ValueError("alibi does not compose with context "
                             "parallelism (ring/ulysses kernels carry no "
                             "position bias)")
        if self.activation not in ("gelu", "gelu_exact", "relu",
                                   "relu2", "swiglu", "geglu"):
            raise ValueError(f"unknown activation {self.activation!r}")
        if self.normalization not in ("layernorm", "rmsnorm"):
            raise ValueError(f"unknown normalization {self.normalization!r}")
        if self.context_parallel_algo not in ("ring", "ulysses"):
            raise ValueError(f"unknown context_parallel_algo "
                             f"{self.context_parallel_algo!r}")
        if self.num_query_groups is not None:
            if (self.num_query_groups < 1
                    or self.num_attention_heads % self.num_query_groups):
                raise ValueError(
                    f"num_attention_heads ({self.num_attention_heads}) must "
                    f"be a positive multiple of num_query_groups "
                    f"({self.num_query_groups})")

    @property
    def ffn_size(self):
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def kv_channels(self):
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def query_groups(self):
        return self.num_query_groups or self.num_attention_heads


def _attn_mask_fn(scores, mask):
    return jnp.where(mask.astype(bool), -10000.0, scores)


_SWA_FLASH_WARNED = set()


def _warn_sliding_window_flash_once(window, seq):
    """Flash supports the window band natively (fmha kernel block-skip),
    but it was unavailable at this call site (non-TPU backend, an
    explicit attention_mask, or seq not a block multiple) — the
    masked-softmax path materializes full [s, s] scores. Trace-time,
    warn once per distinct window so a later, different model that also
    falls back still gets a signal, while variable-length workloads
    (length-bucketed batches retracing many seq values) don't spam one
    warning per length."""
    key = int(window)
    if key in _SWA_FLASH_WARNED:
        return
    _SWA_FLASH_WARNED.add(key)
    import warnings

    warnings.warn(
        f"sliding_window={window} < seq={seq}: flash attention was "
        f"requested but unavailable here (non-TPU backend, explicit "
        f"attention_mask, or seq/head_dim outside the kernel's blocks); "
        f"falling back to masked softmax with O(s^2) score "
        f"materialization.")


def apply_rotary_emb(x, base: float = 10000.0, positions=None,
                     percent: float = 1.0, interleaved: bool = False,
                     scaling: Optional[RopeScaling] = None):
    """Rotary position embedding (rotate-half convention) on [s, b, n, d].

    ``positions`` is [s] (shared across the batch) or [s, b] (per-sequence
    indices, e.g. packed documents); defaults to global indices 0..s-1 —
    correct under sequence parallelism too, because the QKV projections
    gather the full sequence before heads are formed. fp32 trig, cast
    back to x.dtype. ``percent`` < 1 (GPT-NeoX rotary_pct) rotates only
    the leading dims of each head: rotary_ndims = int(d * percent) sets
    the frequency normalization, and 2*ceil(rotary_ndims/2) dims rotate
    (the HF convention — an odd rotary_ndims still pairs up).
    """
    d_full = x.shape[-1]
    if percent < 1.0:
        # +eps: keep HF's trunc semantics while absorbing fp error when
        # percent was derived as rotary_dim / head_dim
        rot_n = int(d_full * percent + 1e-6)  # HF rotary_ndims (may be odd)
        width = 2 * ((rot_n + 1) // 2)  # dims actually rotated
        out = _rope_core(x[..., :width], base, positions, rot_n,
                         interleaved, scaling)
        return jnp.concatenate([out, x[..., width:]], axis=-1)
    return _rope_core(x, base, positions, d_full, interleaved, scaling)


def _rope_core(x, base, positions, freq_dim, interleaved=False,
               scaling=None):
    s, _, _, d = x.shape
    if positions is None:
        positions = jnp.arange(s)
    inv = 1.0 / (base ** (jnp.arange(0, freq_dim, 2, dtype=jnp.float32)
                          / freq_dim))
    if scaling is not None:
        inv = _scale_rope_freqs(inv, scaling)
    freqs = positions[..., None].astype(jnp.float32) * inv  # [s(,b), d/2]
    if freqs.ndim == 2:  # [s, d/2] -> broadcast over batch and heads
        freqs = freqs[:, None, :]
    cos = jnp.cos(freqs)[:, :, None, :]
    sin = jnp.sin(freqs)[:, :, None, :]
    xf = x.astype(jnp.float32)
    if interleaved:  # GPT-J: pairs are (even, odd) lanes
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                        axis=-1).reshape(x.shape)
    else:  # rotate-half: pairs are (i, i + d/2)
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                              -1)
    return out.astype(x.dtype)


def alibi_slopes(num_heads):
    """Per-head alibi slopes (ALiBi paper / HF build_alibi_tensor):
    geometric in 2^(-8/n) for the nearest power-of-two head count,
    interpolated for the remainder."""
    import math

    pow2 = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(pow2) - 3)))
    slopes = [base ** (i + 1) for i in range(pow2)]
    if pow2 < num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * pow2) - 3)))
        slopes += [extra_base ** (2 * i + 1)
                   for i in range(num_heads - pow2)]
    return jnp.asarray(slopes, jnp.float32)


def _local_alibi_slopes(cfg, np_local):
    """This tp rank's slice of the global slope vector (heads are
    contiguously sharded over tp; the canonical rank helper also honors
    the eager set_tensor_model_parallel_rank override)."""
    from apex_tpu.transformer.parallel_state import (
        get_tensor_model_parallel_rank,
    )

    slopes = alibi_slopes(cfg.num_attention_heads)
    rank = get_tensor_model_parallel_rank()
    return jax.lax.dynamic_slice_in_dim(slopes, rank * np_local, np_local)


def _make_norm(cfg, name):
    if cfg.normalization == "rmsnorm":
        from apex_tpu.normalization import FusedRMSNorm

        return FusedRMSNorm(normalized_shape=cfg.hidden_size,
                            eps=cfg.layernorm_epsilon,
                            param_dtype=jnp.float32, name=name)
    if cfg.normalization != "layernorm":
        raise ValueError(f"unknown normalization {cfg.normalization!r}")
    return FusedLayerNorm(normalized_shape=cfg.hidden_size,
                          eps=cfg.layernorm_epsilon,
                          param_dtype=jnp.float32, name=name)


class ParallelAttention(nn.Module):
    """Self-attention with column-parallel QKV + row-parallel projection
    (reference standalone_transformer_lm.py ParallelAttention).

    ``decode=True`` enables KV-cache incremental decoding: 'cache'
    variables hold rotated K/V (group heads, pre-GQA-broadcast) for
    ``max_position_embeddings`` positions; each call appends its ``s``
    tokens at ``cache_index`` and attends over the filled prefix. Apply
    with ``mutable=["cache"]``; works for the prefill chunk (s = prompt
    length) and single-token steps alike.
    """

    config: TransformerConfig
    decode: bool = False
    # which layer this is — selects local vs global attention under
    # sliding_window_pattern (Gemma-2/3 alternation)
    layer_number: int = 0

    def _layer_window(self):
        """This layer's sliding window, or None when it runs full causal
        attention (every sliding_window_pattern-th layer)."""
        cfg = self.config
        if cfg.sliding_window is None:
            return None
        if (cfg.sliding_window_pattern > 1
                and (self.layer_number + 1) % cfg.sliding_window_pattern
                == 0):
            return None
        return cfg.sliding_window

    def _layer_uses_rope(self):
        """False on SmolLM3-style NoPE layers (every interval-th)."""
        cfg = self.config
        if not cfg.no_rope_layer_interval:
            return True
        return (self.layer_number + 1) % cfg.no_rope_layer_interval != 0

    def _layer_rope(self):
        """(rotary_base, rope_scaling) for THIS layer: Gemma-3 gives the
        windowed (local) layers their own base with no frequency
        rescaling, while global layers keep rotary_base/rope_scaling."""
        cfg = self.config
        if (cfg.rotary_base_local is not None
                and self._layer_window() is not None):
            return cfg.rotary_base_local, None
        return cfg.rotary_base, cfg.rope_scaling

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, position_ids=None):
        cfg = self.config
        tp = get_tensor_model_parallel_world_size()
        np_local = cfg.num_attention_heads // tp
        kv = cfg.kv_channels
        s, b, h = hidden_states.shape[-3:]
        x = hidden_states.astype(cfg.compute_dtype)
        if self.decode and cfg.sequence_parallel:
            raise ValueError("decode mode does not compose with "
                             "sequence parallelism")

        if cfg.query_groups == cfg.num_attention_heads:
            qkv = ColumnParallelLinear(
                input_size=cfg.hidden_size,
                output_size=3 * cfg.num_attention_heads * kv,
                gather_output=False, bias=True, params_dtype=cfg.params_dtype,
                sequence_parallel_enabled=cfg.sequence_parallel,
                name="query_key_value")(x)
            # [s, b, 3*h/tp] -> [s, b, np_local, 3*kv]
            seq_full = qkv.shape[0]
            qkv = qkv.reshape(seq_full, b, np_local, 3 * kv)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            # Grouped-query attention: fewer K/V head groups; ONE fused
            # projection (a single SP all-gather / matmul dispatch) whose
            # per-rank columns lay out as [q heads | kv groups] — each tp
            # rank holds whole groups, and per-rank pairing is
            # self-consistent because shards are initialized per rank.
            from apex_tpu.transformer.tensor_parallel.utils import divide

            g_local = divide(cfg.query_groups, tp)
            proj = ColumnParallelLinear(
                input_size=cfg.hidden_size,
                output_size=(cfg.num_attention_heads
                             + 2 * cfg.query_groups) * kv,
                gather_output=False, bias=True, params_dtype=cfg.params_dtype,
                sequence_parallel_enabled=cfg.sequence_parallel,
                name="query_key_value")(x)
            seq_full = proj.shape[0]
            q = proj[..., :np_local * kv].reshape(seq_full, b, np_local, kv)
            kvp = proj[..., np_local * kv:].reshape(seq_full, b, g_local,
                                                    2 * kv)
            k, v = jnp.split(kvp, 2, axis=-1)

        if cfg.qkv_clip is not None:  # DBRX: clamp projection outputs
            clip = jnp.asarray(cfg.qkv_clip, q.dtype)
            q = jnp.clip(q, -clip, clip)
            k = jnp.clip(k, -clip, clip)
            v = jnp.clip(v, -clip, clip)

        if cfg.qk_norm is not None:
            q, k = self._apply_qk_norm(cfg, q, k, tp)

        if self.decode:
            if attention_mask is not None:
                raise ValueError(
                    "decode mode does not support attention_mask: batch "
                    "unpadded prompts (left-trim or group by length)")
            if cfg.context_parallel:
                raise ValueError("decode mode does not compose with "
                                 "context parallelism")
            return self._decode_attention(cfg, q, k, v, position_ids,
                                          np_local, kv, b)

        if cfg.context_parallel:
            if attention_mask is not None:
                raise ValueError("context parallelism supports only the "
                                 "built-in causal/full patterns, not an "
                                 "explicit attention_mask")
            return self._ring_attention(cfg, q, k, v, position_ids,
                                        np_local, kv, b)

        if (cfg.position_embedding_type == "rope"
                and self._layer_uses_rope()):
            rope_base, rope_scale = self._layer_rope()
            q = apply_rotary_emb(q, rope_base, position_ids,
                                 cfg.rotary_percent,
                                 cfg.rotary_interleaved,
                                 rope_scale)
            k = apply_rotary_emb(k, rope_base, position_ids,
                                 cfg.rotary_percent,
                                 cfg.rotary_interleaved,
                                 rope_scale)
        if k.shape[2] != np_local:
            # broadcast each K/V group to its query heads
            rep = np_local // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        # a window covering the whole sequence is plain causal
        layer_win = self._layer_window()
        win = (layer_win
               if (layer_win is not None and layer_win < seq_full)
               else None)

        # flash handles the built-in causal/full patterns and the
        # sliding-window band (kernel block-skip); an explicit
        # attention_mask (e.g. padding), a softcap, or a non-default
        # softmax scale must take the masked softmax path below or they
        # would be silently ignored.
        if (cfg.use_flash_attention and attention_mask is None
                and cfg.attn_logit_softcapping is None
                and cfg.query_pre_attn_scalar in (None, kv)
                and _flash_available(seq_full, kv)):
            from apex_tpu.contrib.fmha import flash_attention

            slopes = (_local_alibi_slopes(cfg, np_local)
                      if cfg.position_embedding_type == "alibi" else None)
            # [s, b, n, d] -> [b, n, s, d]
            qt = q.transpose(1, 2, 0, 3)
            kt = k.transpose(1, 2, 0, 3)
            vt = v.transpose(1, 2, 0, 3)
            ctx = flash_attention(
                qt, kt, vt,
                causal=(cfg.attn_mask_type == AttnMaskType.causal),
                window=win, alibi_slopes=slopes)
            ctx = ctx.transpose(2, 0, 1, 3)  # [s, b, n, d]
        else:
            if win is not None:
                # fold the window band into the mask (masked-softmax path
                # materializes full [s, s] scores — warn when the caller
                # asked for flash but it was unavailable here)
                if cfg.use_flash_attention:
                    _warn_sliding_window_flash_once(win, seq_full)
                i = jnp.arange(seq_full)[:, None]
                j = jnp.arange(seq_full)[None, :]
                band = (j > i) | (i - j >= win)
                attention_mask = (band if attention_mask is None
                                  else band | attention_mask.astype(bool))
            # core attention (reference CoreAttention): [b, n, s, s] scores
            qt = q.transpose(1, 2, 0, 3).astype(cfg.compute_dtype)
            kt = k.transpose(1, 2, 0, 3).astype(cfg.compute_dtype)
            vt = v.transpose(1, 2, 0, 3).astype(cfg.compute_dtype)
            scores = jnp.einsum("bnsd,bntd->bnst", qt, kt,
                                preferred_element_type=jnp.float32)
            scores = scores / jnp.sqrt(
                cfg.query_pre_attn_scalar or kv).astype(jnp.float32)
            if cfg.attn_logit_softcapping is not None:
                # Gemma-2: scale, then cap * tanh(s / cap), then mask
                cap = jnp.float32(cfg.attn_logit_softcapping)
                scores = cap * jnp.tanh(scores / cap)
            if cfg.position_embedding_type == "alibi":
                # key-position-only form (HF build_alibi_tensor): each
                # row differs from slope*(j - i) by a constant, which
                # softmax cancels
                slopes = _local_alibi_slopes(cfg, np_local)
                scores = scores + (slopes[None, :, None, None]
                                   * jnp.arange(seq_full, dtype=jnp.float32
                                                )[None, None, None, :])
            from apex_tpu.transformer.functional.fused_softmax import (
                scaled_masked_softmax,
                scaled_upper_triang_masked_softmax,
            )

            if (cfg.attn_mask_type == AttnMaskType.causal
                    and attention_mask is None):
                bsz, nh, sq, sk = scores.shape
                probs = scaled_upper_triang_masked_softmax(
                    scores.reshape(bsz * nh, sq, sk), 1.0
                ).reshape(bsz, nh, sq, sk)
            else:
                probs = scaled_masked_softmax(scores, attention_mask, 1.0)
            ctx = jnp.einsum("bnst,bntd->bnsd", probs.astype(cfg.compute_dtype), vt,
                             preferred_element_type=jnp.float32)
            ctx = ctx.transpose(2, 0, 1, 3)  # [s, b, n, d]

        ctx = ctx.reshape(ctx.shape[0], b, np_local * kv)
        return self._output_proj(cfg, ctx)

    def _apply_qk_norm(self, cfg, q, k, tp):
        """Query/key RMSNorm before rope (fp32, cast back).

        "projection" (HF modeling_olmoe OlmoeAttention: q_norm/k_norm
        over the FULL projected vector before the head reshape) —
        normalizes across all heads jointly, so a tp-sharded projection
        would need a cross-rank psum of squares; refused for tp > 1.
        "head" (Qwen3 convention): per-head over head_dim — tp-safe."""
        from apex_tpu.normalization import FusedRMSNorm

        def norm(x, shape, name):
            return FusedRMSNorm(
                normalized_shape=shape, eps=cfg.layernorm_epsilon,
                param_dtype=jnp.float32, name=name)(
                x.astype(jnp.float32)).astype(cfg.compute_dtype)

        if cfg.qk_norm == "head":
            return (norm(q, q.shape[-1], "q_norm"),
                    norm(k, k.shape[-1], "k_norm"))
        if tp > 1:
            raise ValueError(
                "qk_norm='projection' normalizes the full projection "
                "width and is not tensor-parallel (would need a psum of "
                "squares across ranks); use tp=1 or qk_norm='head'")
        s, b = q.shape[:2]
        qn = norm(q.reshape(s, b, -1), q.shape[-2] * q.shape[-1], "q_norm")
        kn = norm(k.reshape(s, b, -1), k.shape[-2] * k.shape[-1], "k_norm")
        return qn.reshape(q.shape), kn.reshape(k.shape)

    def _output_proj(self, cfg, ctx):
        """Shared row-parallel output projection (both attention paths —
        keep them on ONE 'dense' module so numerics can't diverge)."""
        return RowParallelLinear(
            input_size=cfg.num_attention_heads * cfg.kv_channels,
            output_size=cfg.hidden_size,
            input_is_parallel=True, bias=True, params_dtype=cfg.params_dtype,
            sequence_parallel_enabled=(cfg.sequence_parallel
                                       and not self.decode),
            name="dense")(ctx.astype(cfg.compute_dtype))

    def _ring_attention(self, cfg, q, k, v, position_ids, np_local, kv, b):
        """Context-parallel core: hidden states are sequence shards over
        the 'cp' axis and activations never materialize the full
        sequence — K/V rotate around the ring (ppermute) or, with
        ``context_parallel_algo="ulysses"``, two all_to_alls trade seq
        sharding for head sharding around a local full attention. RoPE
        uses global positions (cp_rank * s_local + i) so shards agree
        with the unsharded model."""
        from jax import lax

        from apex_tpu.transformer.context_parallel import (
            ring_self_attention,
            ulysses_self_attention,
        )
        from apex_tpu.transformer.parallel_state import CONTEXT_PARALLEL_AXIS

        s = q.shape[0]
        if (cfg.position_embedding_type == "rope"
                and self._layer_uses_rope()):
            if position_ids is None:
                try:
                    rank = lax.axis_index(CONTEXT_PARALLEL_AXIS)
                except Exception:
                    rank = 0
                position_ids = rank * s + jnp.arange(s)
            rope_base, rope_scale = self._layer_rope()
            q = apply_rotary_emb(q, rope_base, position_ids,
                                 cfg.rotary_percent,
                                 cfg.rotary_interleaved,
                                 rope_scale)
            k = apply_rotary_emb(k, rope_base, position_ids,
                                 cfg.rotary_percent,
                                 cfg.rotary_interleaved,
                                 rope_scale)
        if k.shape[2] != np_local:
            rep = np_local // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        attn = (ulysses_self_attention
                if cfg.context_parallel_algo == "ulysses"
                else ring_self_attention)
        # [s, b, n, d] -> [b, s, n, d]
        ctx = attn(
            q.transpose(1, 0, 2, 3).astype(cfg.compute_dtype),
            k.transpose(1, 0, 2, 3).astype(cfg.compute_dtype),
            v.transpose(1, 0, 2, 3).astype(cfg.compute_dtype),
            causal=(cfg.attn_mask_type == AttnMaskType.causal))
        ctx = ctx.transpose(1, 0, 2, 3).reshape(s, b, np_local * kv)
        return self._output_proj(cfg, ctx)

    def _decode_attention(self, cfg, q, k, v, position_ids, np_local, kv, b):
        """KV-cache path: rotate at absolute positions, append to the
        cache, attend over the filled prefix. The cache keeps K/V at
        group granularity and the attention einsums are grouped
        ([b, g, rep, s, t]) — no head-broadcast copy of the full cache
        per step (the GQA memory saving survives decode)."""
        s = q.shape[0]
        n_kv = k.shape[2]
        rep = np_local // n_kv
        max_len = cfg.max_position_embeddings
        initialized = self.has_variable("cache", "cached_key")
        ck = self.variable("cache", "cached_key", jnp.zeros,
                           (max_len, b, n_kv, kv), cfg.compute_dtype)
        cv = self.variable("cache", "cached_value", jnp.zeros,
                           (max_len, b, n_kv, kv), cfg.compute_dtype)
        ci = self.variable("cache", "cache_index",
                           lambda: jnp.zeros((), jnp.int32))
        idx = ci.value
        if (cfg.position_embedding_type == "rope"
                and self._layer_uses_rope()):
            pos = (position_ids if position_ids is not None
                   else idx + jnp.arange(s))
            rope_base, rope_scale = self._layer_rope()
            q = apply_rotary_emb(q, rope_base, pos,
                                 cfg.rotary_percent,
                                 cfg.rotary_interleaved,
                                 rope_scale)
            k = apply_rotary_emb(k, rope_base, pos,
                                 cfg.rotary_percent,
                                 cfg.rotary_interleaved,
                                 rope_scale)
        if not initialized:
            # init pass: create the variables, plain causal attention over
            # the given tokens (shapes/params identical to the real path)
            k_full, v_full, kv_len, offset = k, v, s, jnp.zeros((), jnp.int32)
        else:
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(cfg.compute_dtype), (idx, 0, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(cfg.compute_dtype), (idx, 0, 0, 0))
            ci.value = idx + s
            k_full, v_full, kv_len, offset = ck.value, cv.value, max_len, idx
        qg = q.reshape(s, b, n_kv, rep, kv).astype(cfg.compute_dtype)
        kt = k_full.astype(cfg.compute_dtype)
        vt = v_full.astype(cfg.compute_dtype)
        if (s == 1 and initialized
                and cfg.position_embedding_type != "alibi"):
            # serving hot loop: stream the cache through VMEM once per
            # (batch, group) with tile skipping beyond the prefix and,
            # for windowed layers, before the window (contrib/gqa_decode)
            from apex_tpu.contrib import gqa_decode

            if gqa_decode.use_flash(kv_len):
                import math

                sm = 1.0 / math.sqrt(cfg.query_pre_attn_scalar or kv)
                ctx = gqa_decode.gqa_flash_decode(
                    qg[0], kt, vt, idx + s, sm,
                    window=self._layer_window(),
                    softcap=cfg.attn_logit_softcapping)
                ctx = ctx.reshape(1, b, np_local * kv)
                return self._output_proj(cfg, ctx)
        if (s > 1 and initialized
                and cfg.position_embedding_type != "alibi"):
            # speculative verify window (and any multi-token decode
            # chunk): one flash kernel over the s-position window
            # instead of materializing [b, g, rep, s, T] scores
            # (kernels/fused_cc, family b)
            from apex_tpu.kernels import fused_cc

            if fused_cc.use_window(kv_len):
                import math

                sm = 1.0 / math.sqrt(cfg.query_pre_attn_scalar or kv)
                ctx = fused_cc.window_attention(
                    qg, kt, vt, offset, sm,
                    window=self._layer_window(),
                    softcap=cfg.attn_logit_softcapping)
                ctx = ctx.reshape(s, b, np_local * kv)
                return self._output_proj(cfg, ctx)
        scores = jnp.einsum("sbgrd,tbgd->bgrst", qg, kt,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(
            cfg.query_pre_attn_scalar or kv).astype(jnp.float32)
        if cfg.attn_logit_softcapping is not None:
            cap = jnp.float32(cfg.attn_logit_softcapping)
            scores = cap * jnp.tanh(scores / cap)
        # causal over absolute positions: query i (at offset+i) sees keys
        # j <= offset+i; unfilled cache tail is masked the same way
        if cfg.position_embedding_type == "alibi":
            slopes = _local_alibi_slopes(cfg, n_kv * rep).reshape(
                n_kv, rep)
            scores = scores + (slopes[None, :, :, None, None]
                               * jnp.arange(kv_len, dtype=jnp.float32
                                            )[None, None, None, None, :])
        jpos = jnp.arange(kv_len)[None, :]
        ipos = offset + jnp.arange(s)[:, None]
        masked = jpos > ipos
        decode_win = self._layer_window()
        if decode_win is not None:
            # stale cache entries beyond the window stay resident but
            # invisible (Mistral semantics: 0 <= i - j < window)
            masked = masked | (ipos - jpos >= decode_win)
        scores = jnp.where(masked, -1e30, scores)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bgrst,tbgd->sbgrd",
                         probs.astype(cfg.compute_dtype), vt,
                         preferred_element_type=jnp.float32)
        ctx = ctx.reshape(s, b, np_local * kv)
        return self._output_proj(cfg, ctx)


def _flash_available(seq, head_dim):
    try:
        import jax

        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    return seq % 128 == 0 and head_dim in (64, 128, 256)


class ParallelMLP(nn.Module):
    """h -> 4h (column) -> gelu -> 4h -> h (row)
    (reference standalone_transformer_lm.py ParallelMLP)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden_states):
        cfg = self.config
        if cfg.activation in ("swiglu", "geglu"):
            # Fused [gate | up] projection: each tp rank's local columns
            # split into its own gate/up halves (per-rank pairing is
            # self-consistent because shards are initialized per rank,
            # not sliced from a global matrix). geglu (Gemma family)
            # gates with tanh-approx gelu instead of silu.
            gate_up = ColumnParallelLinear(
                input_size=cfg.hidden_size, output_size=2 * cfg.ffn_size,
                gather_output=False, bias=False,
                params_dtype=cfg.params_dtype,
                sequence_parallel_enabled=cfg.sequence_parallel,
                name="dense_h_to_4h")(hidden_states.astype(cfg.compute_dtype))
            gate, up = jnp.split(gate_up.astype(jnp.float32), 2, axis=-1)
            act = jax.nn.silu if cfg.activation == "swiglu" else jax.nn.gelu
            x = (act(gate) * up).astype(cfg.compute_dtype)
        elif cfg.activation in ("gelu", "gelu_exact", "relu", "relu2"):
            x = ColumnParallelLinear(
                input_size=cfg.hidden_size, output_size=cfg.ffn_size,
                gather_output=False, bias=True, params_dtype=cfg.params_dtype,
                sequence_parallel_enabled=cfg.sequence_parallel,
                name="dense_h_to_4h")(hidden_states.astype(cfg.compute_dtype))
            xf = x.astype(jnp.float32)
            if cfg.activation in ("relu", "relu2"):
                xf = jax.nn.relu(xf)
                if cfg.activation == "relu2":  # Nemotron squared ReLU
                    xf = xf * xf
            else:
                xf = jax.nn.gelu(xf, approximate=(cfg.activation == "gelu"))
            x = xf.astype(cfg.compute_dtype)
        else:
            raise ValueError(f"unknown activation {cfg.activation!r}")
        x = RowParallelLinear(
            input_size=cfg.ffn_size, output_size=cfg.hidden_size,
            input_is_parallel=True,
            bias=(cfg.activation in ("gelu", "gelu_exact", "relu",
                                     "relu2")),
            params_dtype=cfg.params_dtype,
            sequence_parallel_enabled=cfg.sequence_parallel,
            name="dense_4h_to_h")(x)
        return x


class ParallelTransformerLayer(nn.Module):
    """Pre-LN transformer block (reference ParallelTransformerLayer)."""

    config: TransformerConfig
    layer_number: int = 0
    decode: bool = False

    def _is_moe_layer(self) -> bool:
        cfg = self.config
        return (cfg.num_moe_experts is not None
                and self.layer_number % cfg.moe_layer_freq == 0)

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, position_ids=None):
        cfg = self.config
        if cfg.pre_norm:
            ln1 = _make_norm(cfg, "input_layernorm")
            ln1_out = ln1(hidden_states.astype(jnp.float32)).astype(
                cfg.compute_dtype)
        else:  # OLMo-2: the attention branch reads the raw stream
            ln1_out = hidden_states.astype(cfg.compute_dtype)
        attn_out = ParallelAttention(cfg, decode=self.decode,
                                     layer_number=self.layer_number,
                                     name="self_attention")(
            ln1_out, attention_mask, position_ids)
        if cfg.sandwich_norm:
            # Gemma-2: norm each branch's OUTPUT before its residual add
            attn_out = _make_norm(cfg, "post_self_attn_norm")(
                attn_out.astype(jnp.float32)).astype(cfg.compute_dtype)
        rm = cfg.residual_multiplier
        if rm != 1.0:  # Granite: x + m * branch(...)
            attn_out = attn_out * jnp.asarray(rm, attn_out.dtype)
        residual = hidden_states  # pre-attn input (parallel-residual form)
        if not cfg.parallel_residual:
            hidden_states = hidden_states + attn_out.astype(
                hidden_states.dtype)
        # Phi/Falcon-7b: no second norm — both branches read ln1's
        # output. OLMo-2 (pre_norm=False): no pre-MLP norm either — the
        # MLP reads the post-attention residual stream raw.
        ln2 = (None if (cfg.parallel_residual_shared_ln
                        or not cfg.pre_norm)
               else _make_norm(cfg, "post_attention_layernorm"))
        if self._is_moe_layer() and cfg.moe_shared_expert_size:
            from apex_tpu.transformer.moe.layer import SharedExpertMoE

            mlp = SharedExpertMoE(
                hidden_size=cfg.hidden_size,
                ffn_hidden_size=cfg.ffn_size,
                shared_expert_size=cfg.moe_shared_expert_size,
                num_experts=cfg.num_moe_experts, top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                jitter_eps=cfg.moe_jitter_eps,
                router_type=cfg.moe_router_type,
                normalize_topk=cfg.moe_normalize_topk,
                dispatch_mode=cfg.moe_dispatch_mode,
                activation=cfg.activation,
                shared_expert_gated=cfg.moe_shared_expert_gated,
                params_dtype=cfg.params_dtype,
                compute_dtype=cfg.compute_dtype,
                sequence_parallel_enabled=cfg.sequence_parallel, name="mlp")
        elif self._is_moe_layer():
            from apex_tpu.transformer.moe import SwitchMLP

            mlp = SwitchMLP(
                hidden_size=cfg.hidden_size,
                ffn_hidden_size=cfg.ffn_size,
                num_experts=cfg.num_moe_experts, top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                jitter_eps=cfg.moe_jitter_eps,
                router_type=cfg.moe_router_type,
                dispatch_mode=cfg.moe_dispatch_mode,
                normalize_topk=cfg.moe_normalize_topk,
                activation=cfg.activation,
                params_dtype=cfg.params_dtype,
                compute_dtype=cfg.compute_dtype,
                sequence_parallel_enabled=cfg.sequence_parallel, name="mlp")
        else:
            mlp = ParallelMLP(cfg, name="mlp")
        if ln2 is not None:
            mlp_in = ln2(hidden_states.astype(jnp.float32)).astype(
                cfg.compute_dtype)
        elif not cfg.pre_norm:
            # OLMo-2: the MLP reads the post-attention residual raw
            mlp_in = hidden_states.astype(cfg.compute_dtype)
        else:  # Phi/Falcon-7b shared-LN: both branches read ln1's output
            mlp_in = ln1_out
        mlp_out = mlp(mlp_in)
        if cfg.sandwich_norm:
            mlp_out = _make_norm(cfg, "post_mlp_norm")(
                mlp_out.astype(jnp.float32)).astype(cfg.compute_dtype)
        if rm != 1.0:
            mlp_out = mlp_out * jnp.asarray(rm, mlp_out.dtype)
        if cfg.parallel_residual:
            # GPT-NeoX form: both branches read the SAME input (ln2 is
            # applied to the pre-attn stream) and sum into one residual
            return (residual + attn_out.astype(residual.dtype)
                    + mlp_out.astype(residual.dtype))
        return hidden_states + mlp_out.astype(hidden_states.dtype)


class _ScanBlock(nn.Module):
    """lax.scan body for ParallelTransformer(scan_layers=True): one
    uniform layer, (carry, out) signature; params carry a leading
    [num_layers] axis under 'layers/layer'."""

    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, hidden_states, attention_mask, position_ids):
        h = ParallelTransformerLayer(self.config, layer_number=0,
                                     decode=self.decode,
                                     name="layer")(hidden_states,
                                                   attention_mask,
                                                   position_ids)
        return h, None


class ParallelTransformer(nn.Module):
    """A stack of layers, optionally rematerialized per layer
    (reference ParallelTransformer with activation checkpointing -> here
    ``jax.checkpoint`` over each layer)."""

    config: TransformerConfig
    num_layers: Optional[int] = None
    # None -> follow config.activation_checkpointing
    activation_checkpointing: Optional[bool] = None
    decode: bool = False

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, position_ids=None):
        cfg = self.config
        n = self.num_layers if self.num_layers is not None else cfg.num_layers
        remat_on = (cfg.activation_checkpointing
                    if self.activation_checkpointing is None
                    else self.activation_checkpointing)
        if cfg.scan_layers:
            if cfg.num_moe_experts is not None and cfg.moe_layer_freq != 1:
                raise ValueError(
                    "scan_layers needs a uniform stack: moe_layer_freq "
                    "must be 1 (every layer MoE) or num_moe_experts None")
            block = _ScanBlock
            if remat_on and not self.decode:
                block = nn.remat(block, static_argnums=(),
                                 prevent_cse=False)
            scanned = nn.scan(
                block,
                variable_axes={"params": 0, "moe_losses": 0, "cache": 0},
                # split 'jitter' too: un-listed rng streams are DROPPED by
                # nn.scan, which would silently disable router jitter
                split_rngs={"params": True, "jitter": True},
                in_axes=(nn.broadcast, nn.broadcast), length=n,
                metadata_params={nn.PARTITION_NAME: None})
            h, _ = scanned(cfg, decode=self.decode, name="layers")(
                hidden_states, attention_mask, position_ids)
            return h
        layer = ParallelTransformerLayer
        if remat_on and not self.decode:
            layer = nn.checkpoint(ParallelTransformerLayer,
                                  static_argnums=())
        for i in range(n):
            hidden_states = layer(cfg, layer_number=i, decode=self.decode,
                                  name=f"layer_{i}")(
                hidden_states, attention_mask, position_ids)
        return hidden_states


def is_sequence_parallel_param(path: str) -> bool:
    """Path predicate for ``allreduce_sequence_parallel_grads`` on this
    model family: layernorm scales/biases, position embeddings, and the
    replicated biases of the row-parallel linears ('dense', 'dense_4h_to_h')
    are seq-partial under sequence parallelism. Column-parallel biases
    ('query_key_value', 'dense_h_to_4h') are per-rank shards with complete
    grads and must NOT be reduced."""
    if "layernorm" in path or "position_embeddings" in path:
        return True
    if path.endswith("bias"):
        parent = path.rsplit("/", 1)[0].rsplit("/", 1)[-1]
        return parent in ("dense", "dense_4h_to_h")
    return False
