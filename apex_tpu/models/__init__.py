"""apex_tpu.models — model families used by examples, tests and benches.

Parity: reference apex/transformer/testing/standalone_transformer_lm.py
(GPT/BERT Megatron models, 1,574 LoC), examples/imagenet (ResNet),
examples/dcgan (DCGAN).
"""

from apex_tpu.models.transformer_lm import (  # noqa: F401
    ParallelAttention,
    ParallelMLP,
    ParallelTransformerLayer,
    ParallelTransformer,
    TransformerConfig,
)
from apex_tpu.models.gpt import GPTModel, gpt_loss_fn  # noqa: F401
from apex_tpu.models.generation import (  # noqa: F401
    beam_search,
    generate,
    init_cache,
    init_params_tp,
    prefill_prefix,
    sample_logits,
    speculative_generate,
    tensor_parallel_beam_search,
    tensor_parallel_generate,
    verify_step,
)
from apex_tpu.models.tp_split import (  # noqa: F401
    split_mla_params_for_tp,
    split_params_for_tp,
    split_t5_params_for_tp,
)
from apex_tpu.models.t5 import (  # noqa: F401
    T5Config,
    T5Model,
    t5_beam_generate,
    t5_cached_generate,
    t5_greedy_generate,
    t5_loss_fn,
    tensor_parallel_t5_generate,
)
from apex_tpu.models.reshard import (  # noqa: F401
    load_checkpoint_for_3d,
    load_moe_checkpoint_for_ep,
    split_gpt_params_for_pp,
    split_moe_params_for_ep,
)
from apex_tpu.models.bert import BertModel, bert_loss_fn  # noqa: F401
from apex_tpu.models.resnet import ResNet, ResNet18, ResNet50  # noqa: F401
from apex_tpu.models.dcgan import Discriminator, Generator  # noqa: F401
from apex_tpu.models.vit import (  # noqa: F401
    ViTModel,
    vit_config,
    vit_loss_fn,
)
from apex_tpu.models.whisper import (  # noqa: F401
    WhisperConfig,
    WhisperModel,
    whisper_beam_generate,
    whisper_cached_generate,
    whisper_greedy_generate,
)
from apex_tpu.models.mla import (  # noqa: F401
    DeepseekModel,
    MLAConfig,
    mla_cached_generate,
    mla_greedy_generate,
)
