"""DCGAN generator/discriminator (NHWC).

Parity: reference examples/dcgan/main_amp.py models (standard DCGAN:
transposed-conv generator, strided-conv discriminator, BN + (leaky)ReLU) —
the multi-loss amp example (``num_losses=3``).
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    ngf: int = 64
    nc: int = 3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, z, train: bool = True):
        # z: [b, 1, 1, nz]
        x = z.astype(self.dtype)
        norm = lambda name: nn.BatchNorm(use_running_average=not train,  # noqa: E731
                                         dtype=self.dtype,
                                         param_dtype=jnp.float32, name=name)
        x = nn.ConvTranspose(self.ngf * 8, (4, 4), (1, 1), padding="VALID",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm("bn1")(x))
        x = nn.ConvTranspose(self.ngf * 4, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm("bn2")(x))
        x = nn.ConvTranspose(self.ngf * 2, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm("bn3")(x))
        x = nn.ConvTranspose(self.ngf, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm("bn4")(x))
        x = nn.ConvTranspose(self.nc, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        return jnp.tanh(x.astype(jnp.float32))


class Discriminator(nn.Module):
    ndf: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, img, train: bool = True):
        x = img.astype(self.dtype)
        norm = lambda name: nn.BatchNorm(use_running_average=not train,  # noqa: E731
                                         dtype=self.dtype,
                                         param_dtype=jnp.float32, name=name)
        x = nn.Conv(self.ndf, (4, 4), (2, 2), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(self.ndf * 2, (4, 4), (2, 2), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.leaky_relu(norm("bn1")(x), 0.2)
        x = nn.Conv(self.ndf * 4, (4, 4), (2, 2), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.leaky_relu(norm("bn2")(x), 0.2)
        x = nn.Conv(self.ndf * 8, (4, 4), (2, 2), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.leaky_relu(norm("bn3")(x), 0.2)
        x = nn.Conv(1, (4, 4), (1, 1), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)
        return x.reshape(x.shape[0], -1).astype(jnp.float32)
