"""GPT language model on the parallel transformer stack.

Parity: reference apex/transformer/testing/standalone_gpt.py (111 LoC) +
standalone_transformer_lm.py GPTModel: vocab-parallel embedding + learned
positions -> causal ParallelTransformer -> output logits through the tied
embedding (parallel_lm_logits) -> vocab_parallel_cross_entropy.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.transformer_lm import (
    ParallelTransformer,
    TransformerConfig,
    _make_norm,
)
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


class GPTModel(nn.Module):
    """Causal LM. Input token ids [b, s] -> vocab-parallel logits
    [b, s, vocab/tp] (pre-loss; use ``gpt_loss_fn``)."""

    config: TransformerConfig
    num_layers: Optional[int] = None
    pre_process: bool = True   # embed on entry (first pipeline stage)
    post_process: bool = True  # logits+loss on exit (last pipeline stage)
    # KV-cache incremental decoding (apply with mutable=["cache"]). With
    # learned positions, pass explicit position_ids on decode steps (the
    # embed's arange default only suits the prefill chunk); rope offsets
    # come from the cache index automatically.
    decode: bool = False

    @nn.compact
    def __call__(self, tokens, position_ids=None, attention_mask=None,
                 hidden_input=None):
        cfg = self.config
        tp = get_tensor_model_parallel_world_size()

        if self.pre_process:
            emb = VocabParallelEmbedding(
                num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
                params_dtype=cfg.params_dtype, name="word_embeddings")
            h = emb(tokens)
            if cfg.position_embedding_type == "learned":
                if position_ids is None:
                    position_ids = jnp.arange(tokens.shape[-1])[None, :]
                pos = self.param(
                    "position_embeddings", nn.initializers.normal(0.02),
                    (cfg.max_position_embeddings, cfg.hidden_size),
                    cfg.params_dtype)
                h = h + pos[position_ids]
            h = h.astype(cfg.compute_dtype)
            if cfg.embedding_multiplier is not None:
                h = h * jnp.asarray(cfg.embedding_multiplier,
                                    cfg.compute_dtype)
            if cfg.embedding_layernorm:  # BLOOM: LN right after embed
                h = _make_norm(cfg, "embedding_layernorm")(
                    h.astype(jnp.float32)).astype(cfg.compute_dtype)
            # [b, s, h] -> [s, b, h] (Megatron layout: seq-major for SP)
            h = h.transpose(1, 0, 2)
        else:
            h = hidden_input

        # rope consumes positions inside attention (seq-major [s, b]);
        # packed-sequence callers pass per-document position_ids [b, s]
        rope_positions = (position_ids.transpose(1, 0)
                          if (cfg.position_embedding_type == "rope"
                              and position_ids is not None) else None)
        h = ParallelTransformer(cfg, num_layers=self.num_layers,
                                decode=self.decode,
                                name="transformer")(h, attention_mask,
                                                    rope_positions)

        if not self.post_process:
            return h

        h = _make_norm(cfg, "final_layernorm")(h.astype(jnp.float32))
        h = copy_to_tensor_model_parallel_region(h.astype(cfg.compute_dtype))
        if cfg.tie_word_embeddings:
            # Tied head (reference parallel_lm_logits): logits through the
            # embedding table. Requires embed and head on the same program
            # (pre_process and post_process both true — pipeline stages
            # must use the untied head instead).
            if not self.pre_process:
                raise ValueError(
                    "tie_word_embeddings needs the embedding on this "
                    "stage; pipeline-split models must untie")
            logits = emb.attend(h)  # [s, b, vocab/tp]
        else:
            vocab_per_rank = divide(cfg.vocab_size, tp)
            head = self.param(
                "lm_head",
                lambda key, shape, dtype: nn.initializers.normal(0.02)(
                    _fold_tp(key), shape, dtype),
                (cfg.hidden_size, vocab_per_rank), cfg.params_dtype)
            logits = jnp.einsum("sbh,hv->sbv", h,
                                head.astype(cfg.compute_dtype),
                                preferred_element_type=jnp.float32)
            if cfg.lm_head_bias:
                logits = logits + self.param(
                    "lm_head_bias", nn.initializers.zeros,
                    (vocab_per_rank,), cfg.params_dtype).astype(
                        logits.dtype)
        if cfg.logits_scaling != 1.0:
            # Granite: logits are DIVIDED by the scaling (elementwise,
            # shard-safe)
            logits = logits / jnp.asarray(cfg.logits_scaling,
                                          logits.dtype)
        if cfg.final_logit_softcapping is not None:
            # Gemma-2: logits -> cap * tanh(logits / cap), fp32 (HF
            # modeling_gemma2 Gemma2ForCausalLM.forward). Elementwise, so
            # valid on each vocab-parallel shard independently.
            cap = jnp.float32(cfg.final_logit_softcapping)
            logits = (cap * jnp.tanh(logits.astype(jnp.float32) / cap)
                      ).astype(logits.dtype)
        return logits.transpose(1, 0, 2)  # [b, s, vocab/tp]


def _fold_tp(key):
    try:
        rank = jax.lax.axis_index("tp")
    except Exception:
        rank = 0
    return jax.random.fold_in(key, rank)


def gpt_loss_fn(vocab_parallel_logits, labels, loss_mask=None):
    """Mean per-token vocab-parallel CE loss (reference
    standalone_transformer_lm.py post_language_model_processing)."""
    losses = vocab_parallel_cross_entropy(vocab_parallel_logits, labels)
    if loss_mask is not None:
        return jnp.sum(losses * loss_mask) / jnp.maximum(
            jnp.sum(loss_mask), 1.0)
    return jnp.mean(losses)
