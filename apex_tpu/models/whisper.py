"""Whisper encoder-decoder speech model (TPU-native).

The audio modality for the model zoo: mel-spectrogram frames through a
two-conv frontend (stride-2 downsample) + fixed sinusoidal positions
into a pre-LN bidirectional encoder; a causal decoder with learned
positions, cross-attention over the audio states, and a head tied to the
token embedding. Attention is the standard scaled (q * d**-0.5) form
with projection biases (K's bias is identically zero, matching the
original). Rides the same column/row-parallel projections as the rest of
the zoo, so TP/SP/amp facilities apply unchanged.

Reference apex has no speech family; this extends the zoo the same way
MoE/CP do (SURVEY.md §2.3 note) — and exercises the encoder-decoder
machinery (split-rank pipelines, dual payloads) with a second, non-T5
member.
"""

import dataclasses
import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    d_model: int = 512
    encoder_layers: int = 6
    decoder_layers: int = 6
    num_heads: int = 8
    encoder_ffn_dim: int = 2048
    decoder_ffn_dim: int = 2048
    num_mel_bins: int = 80
    max_source_positions: int = 1500   # frames AFTER the stride-2 conv
    max_target_positions: int = 448
    layernorm_epsilon: float = 1e-5
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.d_model % self.num_heads:
            raise ValueError(
                f"d_model ({self.d_model}) must be divisible by "
                f"num_heads ({self.num_heads})")

    @property
    def head_dim(self):
        return self.d_model // self.num_heads


def _ln(cfg, name):
    return FusedLayerNorm(normalized_shape=cfg.d_model,
                          eps=cfg.layernorm_epsilon,
                          param_dtype=jnp.float32, name=name)


class WhisperAttention(nn.Module):
    """Scaled multi-head attention with projection biases; ``cross``
    attends the decoder stream over the encoder memory."""

    config: WhisperConfig
    causal: bool = False
    cross: bool = False  # encoder-decoder attention (memory K/V)

    @nn.compact
    def __call__(self, x_q, x_kv=None, attention_mask=None, mode="train",
                 pos=None):
        """``mode`` (static, mirroring models/t5.py): 'train' — full
        attention; 'prefill' — decode with cache writes (self K/V
        appended at ``pos``; cross K/V of the memory computed once and
        stored); 'step' — decode reading the caches (cross projections
        never re-applied)."""
        cfg = self.config
        tp = get_tensor_model_parallel_world_size()
        n_local = divide(cfg.num_heads, tp)
        d = cfg.head_dim
        sq, b, _ = x_q.shape
        cross = self.cross
        decode = mode in ("prefill", "step")

        def proj(name, src):
            return ColumnParallelLinear(
                input_size=cfg.d_model, output_size=cfg.d_model,
                gather_output=False, bias=True,
                params_dtype=cfg.params_dtype, name=name)(src)

        # q scaled by d**-0.5 BEFORE the matmul (the original's layout;
        # numerically identical to scaling scores)
        q = proj("q", x_q).reshape(sq, b, n_local, d)

        causal_from = None
        if not decode:
            src = x_q if not cross else x_kv
            skv = src.shape[0]
            k = proj("k", src).reshape(skv, b, n_local, d)
            v = proj("v", src).reshape(skv, b, n_local, d)
            if self.causal:
                causal_from = jnp.arange(sq)[:, None]
        elif cross:
            if mode == "prefill":
                skv = x_kv.shape[0]
                k = proj("k", x_kv).reshape(skv, b, n_local, d)
                v = proj("v", x_kv).reshape(skv, b, n_local, d)
                ck = self.variable("cache", "cross_key",
                                   lambda: k.astype(cfg.compute_dtype))
                cv = self.variable("cache", "cross_value",
                                   lambda: v.astype(cfg.compute_dtype))
                ck.value = k.astype(cfg.compute_dtype)
                cv.value = v.astype(cfg.compute_dtype)
            else:
                if not self.has_variable("cache", "cross_key"):
                    raise ValueError(
                        "whisper decode_step before decode_prefill: the "
                        "cross-attention cache is empty")
                k = self.variable("cache", "cross_key", None).value
                v = self.variable("cache", "cross_value", None).value
        else:
            if pos is None:
                raise ValueError("decode self-attention needs pos")
            max_len = cfg.max_target_positions
            k_new = proj("k", x_q).reshape(sq, b, n_local, d)
            v_new = proj("v", x_q).reshape(sq, b, n_local, d)
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (max_len, b, n_local, d), cfg.compute_dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (max_len, b, n_local, d), cfg.compute_dtype)
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k_new.astype(cfg.compute_dtype), (pos, 0, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v_new.astype(cfg.compute_dtype), (pos, 0, 0, 0))
            k, v = ck.value, cv.value
            causal_from = pos + jnp.arange(sq)[:, None]

        scores = jnp.einsum(
            "qbnd,kbnd->bnqk",
            (q * jnp.asarray(d ** -0.5, q.dtype)).astype(cfg.compute_dtype),
            k.astype(cfg.compute_dtype),
            preferred_element_type=jnp.float32)
        if causal_from is not None:
            j = jnp.arange(k.shape[0])[None, :]
            scores = jnp.where(j > causal_from, -1e9, scores)
        if attention_mask is not None and not decode:
            scores = jnp.where(
                attention_mask.astype(bool)[:, None, None, :],
                scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bnqk,kbnd->qbnd",
                         probs.astype(cfg.compute_dtype),
                         v.astype(cfg.compute_dtype),
                         preferred_element_type=jnp.float32)
        ctx = ctx.reshape(sq, b, n_local * d).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=cfg.d_model, output_size=cfg.d_model,
            input_is_parallel=True, bias=True,
            params_dtype=cfg.params_dtype, name="out")(ctx)


class _FFN(nn.Module):
    config: WhisperConfig
    ffn_dim: int

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = ColumnParallelLinear(
            input_size=cfg.d_model, output_size=self.ffn_dim,
            gather_output=False, bias=True,
            params_dtype=cfg.params_dtype, name="fc1")(
            x.astype(cfg.compute_dtype))
        h = jax.nn.gelu(h.astype(jnp.float32),
                        approximate=False).astype(cfg.compute_dtype)
        return RowParallelLinear(
            input_size=self.ffn_dim, output_size=cfg.d_model,
            input_is_parallel=True, bias=True,
            params_dtype=cfg.params_dtype, name="fc2")(h)


class WhisperBlock(nn.Module):
    config: WhisperConfig
    ffn_dim: int
    has_cross: bool = False
    causal: bool = False

    @nn.compact
    def __call__(self, h, memory=None, self_mask=None, mode="train",
                 pos=None):
        cfg = self.config
        x = _ln(cfg, "self_attn_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        h = h + WhisperAttention(cfg, causal=self.causal,
                                 name="self_attn")(
            x, None, self_mask, mode=mode, pos=pos).astype(h.dtype)
        if self.has_cross:
            x = _ln(cfg, "cross_attn_norm")(h.astype(jnp.float32)).astype(
                cfg.compute_dtype)
            h = h + WhisperAttention(cfg, cross=True, name="cross_attn")(
                x, memory, mode=mode).astype(h.dtype)
        x = _ln(cfg, "ffn_norm")(h.astype(jnp.float32)).astype(
            cfg.compute_dtype)
        return h + _FFN(cfg, self.ffn_dim, name="ffn")(x).astype(h.dtype)


def sinusoidal_positions(length, channels):
    """The original Whisper sinusoid table [length, channels]
    (log-spaced timescales, sin | cos halves)."""
    half = channels // 2
    scale = np.log(10000.0) / (half - 1)
    inv = np.exp(-scale * np.arange(half, dtype=np.float64))
    ang = np.arange(length, dtype=np.float64)[:, None] * inv[None, :]
    return jnp.asarray(
        np.concatenate([np.sin(ang), np.cos(ang)], axis=1), jnp.float32)


class WhisperEncoder(nn.Module):
    """[b, num_mel_bins, frames] (the HF layout) -> audio memory
    [s, b, d_model] (fp32 normed)."""

    config: WhisperConfig

    @nn.compact
    def __call__(self, feats):
        cfg = self.config
        # [b, mel, T] -> [b, T, mel] feature-last for the MXU conv path
        x = feats.transpose(0, 2, 1).astype(cfg.compute_dtype)
        x = nn.Conv(cfg.d_model, (3,), padding=[(1, 1)],
                    dtype=cfg.compute_dtype, param_dtype=cfg.params_dtype,
                    name="conv1")(x)
        x = jax.nn.gelu(x.astype(jnp.float32), approximate=False)
        x = nn.Conv(cfg.d_model, (3,), strides=(2,), padding=[(1, 1)],
                    dtype=cfg.compute_dtype, param_dtype=cfg.params_dtype,
                    name="conv2")(x.astype(cfg.compute_dtype))
        x = jax.nn.gelu(x.astype(jnp.float32), approximate=False)
        if x.shape[1] != cfg.max_source_positions:
            raise ValueError(
                f"whisper encoder expects {cfg.max_source_positions} "
                f"post-conv frames, got {x.shape[1]} (feed "
                f"{2 * cfg.max_source_positions} mel frames)")
        pos = self.param("positions",
                         lambda key, shape, dtype: sinusoidal_positions(
                             *shape).astype(dtype),
                         (cfg.max_source_positions, cfg.d_model),
                         cfg.params_dtype)
        h = (x + pos[None]).astype(cfg.compute_dtype).transpose(1, 0, 2)
        for i in range(cfg.encoder_layers):
            h = WhisperBlock(cfg, cfg.encoder_ffn_dim,
                             name=f"block_{i}")(h)
        return _ln(cfg, "final_norm")(h.astype(jnp.float32))


class WhisperDecoder(nn.Module):
    """Embedded decoder tokens + audio memory -> pre-head hidden
    [s, b, d_model] (fp32 normed)."""

    config: WhisperConfig

    @nn.compact
    def __call__(self, h, memory=None, mode="train"):
        cfg = self.config
        s = h.shape[0]
        pos = self.param("positions", nn.initializers.normal(0.02),
                         (cfg.max_target_positions, cfg.d_model),
                         cfg.params_dtype)
        offset = None
        if mode in ("prefill", "step"):
            ctr = self.variable("cache", "pos",
                                lambda: jnp.zeros((), jnp.int32))
            offset = (jnp.zeros((), jnp.int32) if mode == "prefill"
                      else ctr.value)
            ctr.value = offset + s
            h = h + jax.lax.dynamic_slice_in_dim(
                pos, offset, s, axis=0)[:, None].astype(h.dtype)
        else:
            h = h + pos[:s, None].astype(h.dtype)
        if memory is not None:
            memory = memory.astype(cfg.compute_dtype)
        for i in range(cfg.decoder_layers):
            h = WhisperBlock(cfg, cfg.decoder_ffn_dim, has_cross=True,
                             causal=True, name=f"block_{i}")(
                h, memory, mode=mode, pos=offset)
        return _ln(cfg, "final_norm")(h.astype(jnp.float32))


class WhisperModel(nn.Module):
    """``__call__(input_features, dec_tokens)``: mel features
    [b, num_mel_bins, frames] + decoder ids [b, s] -> [b, s, vocab/tp]
    logits (head tied to the token embedding). ``encode`` /
    ``decode_from_memory`` expose the halves for split-rank pipeline
    stages and two-phase transcription."""

    config: WhisperConfig

    def setup(self):
        cfg = self.config
        self.embed_tokens = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.d_model,
            params_dtype=cfg.params_dtype, name="embed_tokens")
        self.encoder = WhisperEncoder(cfg, name="encoder")
        self.decoder = WhisperDecoder(cfg, name="decoder")

    def encode(self, input_features):
        return self.encoder(input_features)

    def _embed(self, dec_tokens):
        return self.embed_tokens(dec_tokens).astype(
            self.config.compute_dtype).transpose(1, 0, 2)

    def _head(self, h):
        h = copy_to_tensor_model_parallel_region(
            h.astype(self.config.compute_dtype))
        logits = self.embed_tokens.attend(h)  # tied head
        return logits.transpose(1, 0, 2)  # [b, s, vocab/tp]

    def decode_from_memory(self, dec_tokens, memory):
        return self._head(self.decoder(self._embed(dec_tokens), memory))

    def decode_prefill(self, dec_tokens, memory):
        """KV-cache decode, phase 1 (apply with ``mutable=["cache"]``):
        runs the decoder prefix, filling self caches and computing the
        cross K/V from ``memory`` once."""
        return self._head(self.decoder(self._embed(dec_tokens), memory,
                                       mode="prefill"))

    def decode_step(self, dec_tokens):
        """KV-cache decode, phase 2: extend against the caches; the
        audio memory is NOT needed (cross K/V read back)."""
        return self._head(self.decoder(self._embed(dec_tokens), None,
                                       mode="step"))

    def __call__(self, input_features, dec_tokens):
        return self.decode_from_memory(dec_tokens,
                                       self.encode(input_features))


def whisper_cached_generate(model, params, input_features, max_new_tokens,
                            decoder_start_token_id):
    """Greedy transcription on the KV-cache path: encode once, prefill
    with the start token, one jitted single-token step per new token
    (cross K/V never re-projected). Token-exact vs
    :func:`whisper_greedy_generate`, its oracle."""
    cfg = model.config
    # slots written: 1 (prefill) + max_new_tokens - 1 steps (the last
    # generated token is never fed back) = max_new_tokens
    if max_new_tokens > cfg.max_target_positions:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) exceeds "
            f"max_target_positions ({cfg.max_target_positions})")
    b = input_features.shape[0]
    start = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    if max_new_tokens == 0:
        return start
    memory = model.apply({"params": params}, input_features,
                         method=WhisperModel.encode)
    prefill, decode_all = _whisper_compiled_decode(model, max_new_tokens)
    cache, first = prefill(params, start, memory)
    if max_new_tokens == 1:
        return jnp.concatenate([start, first[:, None]], axis=1)
    toks = decode_all(params, cache, first)
    return jnp.concatenate([start, first[:, None], toks.T], axis=1)



@functools.lru_cache(maxsize=16)
def _whisper_compiled_decode(model, max_new_tokens):
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    @jax.jit
    def prefill(params, start, memory):
        logits, mut = model.apply(
            {"params": params}, start, memory, mutable=["cache"],
            method=WhisperModel.decode_prefill)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        return mut["cache"], jnp.argmax(full, -1).astype(jnp.int32)

    @jax.jit
    def decode_all(params, cache, first):
        def step(carry, _):
            cache, tok = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"], method=WhisperModel.decode_step)
            full = gather_from_tensor_model_parallel_region(
                logits[:, -1, :])
            nxt = jnp.argmax(full, -1).astype(jnp.int32)
            return (mut["cache"], nxt), nxt
        (_, _), toks = jax.lax.scan(step, (cache, first), None,
                                    length=max_new_tokens - 1)
        return toks

    return prefill, decode_all


@functools.lru_cache(maxsize=16)
def _whisper_compiled_beam(model, max_new_tokens, num_beams, eos_token_id,
                           pad_token_id, length_penalty):
    from apex_tpu.models.encdec_beam import (
        beam_search_cached,
        tile_cache_for_beams,
    )
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    @jax.jit
    def run(params, start, memory):
        logits, mut = model.apply(
            {"params": params}, start, memory, mutable=["cache"],
            method=WhisperModel.decode_prefill)
        first = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        cache = tile_cache_for_beams(mut["cache"], num_beams)

        def step_fn(cache, tok):
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"], method=WhisperModel.decode_step)
            return gather_from_tensor_model_parallel_region(
                logits[:, -1, :]), mut["cache"]

        return beam_search_cached(
            step_fn, cache, first, num_beams=num_beams,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, length_penalty=length_penalty)

    return run


def whisper_beam_generate(model, params, input_features, max_new_tokens,
                          decoder_start_token_id, num_beams=4,
                          eos_token_id=None, pad_token_id=0,
                          length_penalty=1.0):
    """Beam-search transcription on the KV-cache path (HF generate
    semantics — models/encdec_beam.py): encode once, prefill the start
    token, tile the caches per beam, one jitted step per new token with
    per-beam cache reordering (cross K/V tiled once, never
    re-projected). Returns ([b, 1 + max_new] sequences incl the start
    column, [b] final scores)."""
    cfg = model.config
    if max_new_tokens > cfg.max_target_positions:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) exceeds "
            f"max_target_positions ({cfg.max_target_positions})")
    b = input_features.shape[0]
    start = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    if max_new_tokens == 0:
        return start, jnp.zeros((b,), jnp.float32)
    memory = model.apply({"params": params}, input_features,
                         method=WhisperModel.encode)
    run = _whisper_compiled_beam(model, max_new_tokens, num_beams,
                                 eos_token_id, pad_token_id,
                                 float(length_penalty))
    seqs, scores = run(params, start, memory)
    return jnp.concatenate([start, seqs], axis=1), scores


def whisper_greedy_generate(model, params, input_features, max_new_tokens,
                            decoder_start_token_id):
    """Greedy transcription: encode once, full decoder re-run per token
    (oracle path, mirroring t5_greedy_generate)."""
    from apex_tpu.transformer.tensor_parallel import (
        gather_from_tensor_model_parallel_region,
    )

    b = input_features.shape[0]
    memory = model.apply({"params": params}, input_features,
                         method=WhisperModel.encode)
    dec = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    for _ in range(max_new_tokens):
        logits = model.apply({"params": params}, dec, memory,
                             method=WhisperModel.decode_from_memory)
        full = gather_from_tensor_model_parallel_region(logits[:, -1, :])
        nxt = jnp.argmax(full, axis=-1).astype(jnp.int32)
        dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
    return dec
