"""Vision Transformer on the parallel transformer stack.

Parity: the reference carries Megatron's vision model surface in its
launch-flag layer (apex/transformer/testing — vision/DINO argument tails
handled by `testing/arguments.py` here), and its ImageNet example is the
CV half of its model zoo. This supplies the actual model family: a
standard ViT (patch-conv embed, [CLS] token, learned positions, pre-LN
bidirectional blocks with exact-erf gelu, classifier on the CLS state)
riding the SAME tensor/sequence-parallel transformer stack as
GPT/BERT/T5 — so every TP/SP/pipeline/amp facility applies to vision
models unchanged. NHWC images feed the MXU's native conv path.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.transformer_lm import (
    ParallelTransformer,
    TransformerConfig,
)
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.enums import AttnMaskType


def vit_config(hidden_size=768, num_layers=12, num_heads=12,
               ffn_hidden_size=None, layernorm_epsilon=1e-12,
               compute_dtype=jnp.bfloat16, **kw) -> TransformerConfig:
    """TransformerConfig preset for ViT: bidirectional (padding mask
    type), exact-erf gelu (HF ViT convention), no flash (short patch
    sequences; full softmax fuses fine)."""
    return TransformerConfig(
        hidden_size=hidden_size, num_layers=num_layers,
        num_attention_heads=num_heads, ffn_hidden_size=ffn_hidden_size,
        vocab_size=1,  # unused: no token embedding in ViT
        max_position_embeddings=1,
        attn_mask_type=AttnMaskType.padding,
        activation="gelu_exact", use_flash_attention=False,
        layernorm_epsilon=layernorm_epsilon,
        compute_dtype=compute_dtype, **kw)


class ViTModel(nn.Module):
    """[b, H, W, C] NHWC images -> [b, num_classes] logits (or the
    [s, b, h] encoded sequence when ``num_classes`` is None)."""

    config: TransformerConfig
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    num_classes: Optional[int] = 1000

    @nn.compact
    def __call__(self, images):
        cfg = self.config
        assert cfg.attn_mask_type == AttnMaskType.padding, (
            "ViT is bidirectional: build the config with vit_config() "
            "(causal would silently mask future patches)")
        p = self.patch_size
        b = images.shape[0]
        x = nn.Conv(cfg.hidden_size, (p, p), strides=(p, p),
                    dtype=cfg.compute_dtype, param_dtype=cfg.params_dtype,
                    name="patch_embed")(images.astype(cfg.compute_dtype))
        x = x.reshape(b, -1, cfg.hidden_size)  # [b, np, h]
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.hidden_size), cfg.params_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(x.dtype),
                              (b, 1, cfg.hidden_size)), x], axis=1)
        pos = self.param("position_embeddings",
                         nn.initializers.normal(0.02),
                         ((self.image_size // p) ** 2 + 1,
                          cfg.hidden_size), cfg.params_dtype)
        # no silent truncation: a grid/image-size mismatch must raise
        # (HF ViT does the same), not read spatially wrong positions
        x = x + pos[None].astype(x.dtype)
        h = x.transpose(1, 0, 2)  # [s, b, h] Megatron layout
        h = ParallelTransformer(cfg, name="transformer")(h, None)
        h = FusedLayerNorm(normalized_shape=cfg.hidden_size,
                           eps=cfg.layernorm_epsilon,
                           param_dtype=jnp.float32,
                           name="final_layernorm")(h.astype(jnp.float32))
        if self.num_classes is None:
            return h
        return nn.Dense(self.num_classes, param_dtype=cfg.params_dtype,
                        dtype=jnp.float32,
                        name="classifier")(
            h[0].astype(jnp.float32))  # CLS state


def vit_loss_fn(logits, labels):
    """Mean softmax cross-entropy over classes."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
