"""Pipeline-stage view of the GPT model.

Under SPMD pipelining every pp rank runs the *same program* over its own
stage weights, so a "stage" bundles: the embedding (used when
``is_first_stage``), a slice of transformer layers, and the final
LN + LM head + loss (evaluated by the schedule's ``loss_func`` on the last
stage). This mirrors the reference's ``build_model`` with
pre_process/post_process flags (apex/transformer/pipeline_parallel/schedules/
common.py:30-151) re-expressed as masked SPMD branches.
"""


import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.models.gpt import _fold_tp
from apex_tpu.models.transformer_lm import (
    ParallelTransformer,
    TransformerConfig,
    _make_norm,
)
from apex_tpu.transformer.parallel_state import (
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


class GPTStage(nn.Module):
    config: TransformerConfig
    layers_per_stage: int

    def setup(self):
        cfg = self.config
        if cfg.sliding_window_pattern > 1 or cfg.no_rope_layer_interval:
            raise ValueError(
                "per-layer alternation (sliding_window_pattern > 1 or "
                "no_rope_layer_interval) is not supported under SPMD "
                "pipelining: every stage runs the same program with "
                "per-stage layer numbering, so the alternation would "
                "silently restart at each stage boundary")
        self.word_embeddings = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            params_dtype=cfg.params_dtype, name="word_embeddings")
        if cfg.position_embedding_type == "learned":
            self.position_embeddings = self.param(
                "position_embeddings", nn.initializers.normal(0.02),
                (cfg.max_position_embeddings, cfg.hidden_size),
                cfg.params_dtype)
        self.transformer = ParallelTransformer(
            cfg, num_layers=self.layers_per_stage, name="transformer")
        self.final_layernorm = _make_norm(cfg, "final_layernorm")
        self.embedding_layernorm = (
            _make_norm(cfg, "embedding_layernorm")
            if cfg.embedding_layernorm else None)
        tp = get_tensor_model_parallel_world_size()
        self.lm_head = self.param(
            "lm_head",
            lambda key, shape, dtype: nn.initializers.normal(0.02)(
                _fold_tp(key), shape, dtype),
            (cfg.hidden_size, divide(cfg.vocab_size, tp)), cfg.params_dtype)
        self.lm_head_bias = (self.param(
            "lm_head_bias", nn.initializers.zeros,
            (divide(cfg.vocab_size, tp),), cfg.params_dtype)
            if cfg.lm_head_bias else None)

    def embed(self, tokens):
        cfg = self.config
        s = tokens.shape[-1]
        h = self.word_embeddings(tokens)
        if cfg.position_embedding_type == "learned":
            h = h + self.position_embeddings[:s][None, :, :]
        h = h.astype(cfg.compute_dtype)
        if cfg.embedding_multiplier is not None:
            h = h * jnp.asarray(cfg.embedding_multiplier, cfg.compute_dtype)
        if cfg.embedding_layernorm:  # BLOOM: LN right after embed
            h = self.embedding_layernorm(
                h.astype(jnp.float32)).astype(cfg.compute_dtype)
        h = h.transpose(1, 0, 2)  # [s, b, h]
        if cfg.sequence_parallel:
            h = scatter_to_sequence_parallel_region(h)
        return h

    def __call__(self, tokens, h_in, is_first):
        """Stage forward: embed on the first stage, then this stage's
        layers. ``h_in`` is the activation arriving from the previous
        stage (seq-sharded under SP)."""
        e = self.embed(tokens)
        h = jnp.where(is_first, e, h_in.astype(e.dtype))
        return self.transformer(h, None)

    def loss(self, h, labels, loss_mask=None):
        """Last-stage head: final LN -> LM head -> vocab-parallel CE."""
        cfg = self.config
        h = self.final_layernorm(h.astype(jnp.float32))
        if cfg.sequence_parallel:
            h = gather_from_sequence_parallel_region(h.astype(cfg.compute_dtype), True)
        h = copy_to_tensor_model_parallel_region(h.astype(cfg.compute_dtype))
        logits = jnp.einsum("sbh,hv->sbv", h,
                            self.lm_head.astype(cfg.compute_dtype),
                            preferred_element_type=jnp.float32)
        if self.lm_head_bias is not None:
            logits = logits + self.lm_head_bias.astype(logits.dtype)
        if cfg.logits_scaling != 1.0:  # Granite divisor — as in GPTModel
            logits = logits / jnp.asarray(cfg.logits_scaling,
                                          logits.dtype)
        if cfg.final_logit_softcapping is not None:
            # same cap as GPTModel's head — a pipelined softcap model
            # must not silently train on uncapped logits
            cap = jnp.float32(cfg.final_logit_softcapping)
            logits = (cap * jnp.tanh(logits.astype(jnp.float32) / cap)
                      ).astype(logits.dtype)
        logits = logits.transpose(1, 0, 2)  # [b, s, vocab/tp]
        losses = vocab_parallel_cross_entropy(logits, labels)
        if loss_mask is not None:
            return jnp.sum(losses * loss_mask) / jnp.maximum(
                jnp.sum(loss_mask), 1.0)
        return jnp.mean(losses)

    def full(self, tokens, h_in, is_first, labels):
        """Init-path helper touching every parameter."""
        h = self(tokens, h_in, is_first)
        return self.loss(h, labels)
