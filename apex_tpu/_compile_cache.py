"""Opt-in persistent XLA compilation cache (one switch for tests, the
driver dryrun and local tooling).

Compile time dominates the L0 suite and the multichip dryrun on slow
hosts; a warm cache cuts serial wall-clock substantially. Off by default:
XLA:CPU AOT reload can log machine-feature-mismatch errors when the cache
dir migrates across heterogeneous hosts. Enable on a fixed host with e.g.

    APEX_TPU_COMPILE_CACHE=/tmp/apex_tpu_jit_cache pytest tests/L0 -q
"""

import os


def maybe_enable_compile_cache(min_compile_secs: float = 0.5) -> bool:
    """Point jax at $APEX_TPU_COMPILE_CACHE if set. Returns True when
    enabled. Call before the first compilation."""
    cache_dir = os.environ.get("APEX_TPU_COMPILE_CACHE", "")
    if not cache_dir:
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    return True
