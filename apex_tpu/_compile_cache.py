"""Opt-in persistent XLA compilation cache (one switch for tests, the
driver dryrun and local tooling) — with hit/miss observability.

Compile time dominates the L0 suite and the multichip dryrun on slow
hosts; a warm cache cuts serial wall-clock substantially. Off by default:
XLA:CPU AOT reload can log machine-feature-mismatch errors when the cache
dir migrates across heterogeneous hosts. Enable on a fixed host with e.g.

    APEX_TPU_COMPILE_CACHE=/tmp/apex_tpu_jit_cache pytest tests/L0 -q

Enabling also installs ``jax.monitoring`` listeners for the persistent
cache's hit/miss events, so :func:`cache_stats` (and the
``compile_cache/hits`` / ``compile_cache/misses`` telemetry counters)
answer "is the cache actually warm?" — a cache that silently misses
every compile (key drift across jax versions, an evicted dir) costs the
full compile time while looking enabled.
"""

import os
import threading

_ENV_CACHE = "APEX_TPU_COMPILE_CACHE"

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_STATS_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0}
_LISTENER_INSTALLED = False


def _on_cache_event(event, **kwargs):
    if event == _HIT_EVENT:
        key = "hits"
    elif event == _MISS_EVENT:
        key = "misses"
    else:
        return
    with _STATS_LOCK:
        _STATS[key] += 1
    from apex_tpu.telemetry.registry import get_registry

    reg = get_registry()
    if reg.enabled:
        reg.counter(f"compile_cache/{key}").inc()


def install_cache_counters() -> None:
    """Register the (one, idempotent) monitoring listener feeding
    :func:`cache_stats`. jax offers no per-listener removal, so this
    registers once per process; the listener is a counter bump."""
    global _LISTENER_INSTALLED
    with _STATS_LOCK:
        if _LISTENER_INSTALLED:
            return
        _LISTENER_INSTALLED = True
    import jax.monitoring

    jax.monitoring.register_event_listener(_on_cache_event)


def cache_stats() -> dict:
    """``{"hits", "misses"}`` persistent-cache lookups observed since
    :func:`install_cache_counters` ran (0/0 before — counting starts
    when the cache is enabled)."""
    with _STATS_LOCK:
        return dict(_STATS)


def maybe_enable_compile_cache(min_compile_secs: float = 0.5) -> bool:
    """Point jax at $APEX_TPU_COMPILE_CACHE if set. Returns True when
    enabled. Call before the first compilation."""
    cache_dir = os.environ.get(_ENV_CACHE, "")
    if not cache_dir:
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    # jax caches its "is the cache used?" decision once per task; if
    # anything compiled before we set the dir, that decision is a
    # permanent False. Reset it (best-effort, private API) so enabling
    # mid-process actually enables.
    try:
        from jax._src import compilation_cache as _jax_cc

        _jax_cc.reset_cache()
    except Exception:
        pass
    install_cache_counters()
    return True
